"""Serving-stack tests (horovod_tpu/serve): paged KV pool invariants,
pooled-vs-contiguous bitwise parity, scheduler determinism, the SLO
controller's replayable control trace, input validation, the bench
record stale gate, and the two-replica elastic e2e (a replica dies
mid-stream, lease/respawn recovers every sequence token-exactly)."""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.common.exceptions import HorovodTpuError
from horovod_tpu.models import (
    TransformerConfig,
    init_decode_cache,
    transformer_decode_step,
    transformer_generate,
    transformer_init,
    transformer_prefill,
)
from horovod_tpu.serve import (
    ContinuousScheduler,
    InferenceServer,
    PagedKVPool,
    PoolExhaustedError,
    Request,
    SloController,
)
from horovod_tpu.serve.loadgen import (
    append_record,
    make_trace,
    read_latest_record,
    run_trace,
)


def _cfg(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=4, d_head=8,
                d_ff=64, n_layers=2, compute_dtype=jnp.float32)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return cfg, transformer_init(jax.random.PRNGKey(0), cfg)


class TestPagedKVPool:
    def test_alloc_free_reuse_no_leak(self):
        pool = PagedKVPool(_cfg(), total_pages=8, page_tokens=4)
        a = pool.alloc(1, 10)          # 3 pages
        b = pool.alloc(2, 4)           # 1 page
        assert a == [0, 1, 2] and b == [3]
        assert pool.pages_free() == 4
        assert pool.utilization() == pytest.approx(0.5)
        pool.free(1)
        assert pool.pages_free() == 7
        # Deterministic LIFO reuse: the MRU page of the freed list
        # comes back first.
        c = pool.alloc(3, 8)
        assert c == [0, 1]
        pool.free(2)
        pool.free(3)
        assert pool.pages_free() == 8
        assert pool.pages == {}        # no leaked page lists

    def test_exhaustion_and_double_alloc(self):
        pool = PagedKVPool(_cfg(), total_pages=2, page_tokens=4)
        pool.alloc(1, 8)
        with pytest.raises(PoolExhaustedError):
            pool.alloc(2, 4)
        with pytest.raises(HorovodTpuError, match="already holds"):
            pool.alloc(1, 4)
        with pytest.raises(HorovodTpuError, match="holds no pages"):
            pool.free(99)
        assert pool.can_alloc(4) is False
        pool.free(1)
        assert pool.can_alloc(8) is True

    @pytest.mark.parametrize("quantize", [None, "int8"])
    def test_pooled_decode_bitwise_equal(self, model, quantize):
        """The tentpole parity claim: decode over a pooled-page view is
        BITWISE equal to decode over a contiguous cache, because
        gather/scatter is pure data movement.  Both sides start from
        the SAME per-row prefill bytes (a batched prefill may reduce in
        a different order); the pooled side routes them through
        scatter_pages -> gather."""
        cfg, params = model
        B, T0, steps, ring = 2, 4, 5, 16
        toks = np.asarray(jax.random.randint(
            jax.random.PRNGKey(1), (B, T0), 0, 64), np.int32)

        def _cat(a, b):                 # concat caches on the batch axis
            if isinstance(a, dict):
                return {k: jnp.concatenate([a[k], b[k]], axis=1)
                        for k in a}
            return jnp.concatenate([a, b], axis=1)

        pool = PagedKVPool(cfg, total_pages=2 * (ring // 4),
                           page_tokens=4, quantize=quantize)
        ck = cv = lg0 = None
        for b in range(B):
            pool.alloc(b, ring)
            scratch = init_decode_cache(cfg, 1, ring, quantize=quantize)
            plg, scratch = transformer_prefill(
                params, scratch, jnp.asarray(toks[b:b + 1]), cfg)
            pool.scatter_pages(b, scratch["k"], scratch["v"])
            ck = scratch["k"] if ck is None else _cat(ck, scratch["k"])
            cv = scratch["v"] if cv is None else _cat(cv, scratch["v"])
            lg0 = plg if lg0 is None else jnp.concatenate(
                [lg0, plg], axis=0)

        def _np(kv):
            return (np.asarray(kv["q"]) if isinstance(kv, dict)
                    else np.asarray(kv))

        # gather reproduces the installed bytes exactly
        vk, vv = pool.gather([0, 1], ring // 4)
        np.testing.assert_array_equal(_np(vk), _np(ck))
        np.testing.assert_array_equal(_np(vv), _np(cv))

        pos = np.full(B, T0, np.int64)
        tok = jnp.argmax(lg0, -1)
        rtok = tok
        for _ in range(steps):
            p = jnp.asarray(pos, jnp.int32)
            rlg, rc = transformer_decode_step(
                params, {"k": ck, "v": cv, "pos": p}, rtok, cfg)
            ck, cv = rc["k"], rc["v"]
            lg, c = transformer_decode_step(
                params, {"k": vk, "v": vv, "pos": p}, tok, cfg)
            vk, vv = c["k"], c["v"]
            pool.scatter_slots(vk, vv, [0, 1], [0, 1],
                               [int(q) % ring for q in pos])
            np.testing.assert_array_equal(np.asarray(lg),
                                          np.asarray(rlg))
            pos += 1
            tok, rtok = jnp.argmax(lg, -1), jnp.argmax(rlg, -1)
        # The per-step scatter kept the POOL the source of truth: a
        # fresh gather reproduces the contiguous cache bit-for-bit.
        fk, fv = pool.gather([0, 1], ring // 4)
        np.testing.assert_array_equal(_np(fk), _np(ck))
        np.testing.assert_array_equal(_np(fv), _np(cv))

    def test_gather_rows_matches_full_gather(self, model):
        cfg, _ = model
        pool = PagedKVPool(cfg, total_pages=6, page_tokens=4)
        pool.alloc(10, 8)
        pool.alloc(11, 8)
        vk, vv = pool.gather([10, 11, None], 2)
        pool.free(10)
        pool.alloc(12, 8)
        uk, uv = pool.gather_rows(vk, vv, [(2, 12)], 2)
        fk, fv = pool.gather([None, 11, 12], 2)   # row0 stale is fine:
        np.testing.assert_array_equal(            # compare rows 1..2
            np.asarray(uk)[:, 1:], np.asarray(fk)[:, 1:])
        np.testing.assert_array_equal(
            np.asarray(uv)[:, 1:], np.asarray(fv)[:, 1:])

    def test_validation(self):
        with pytest.raises(HorovodTpuError):
            PagedKVPool(_cfg(), total_pages=0, page_tokens=4)
        with pytest.raises(HorovodTpuError):
            PagedKVPool(_cfg(), total_pages=4, page_tokens=0)


class TestScheduler:
    def _run(self, policy, seed=0):
        sched = ContinuousScheduler(3, policy=policy, seed=seed)
        for n in range(8):              # deep queue: policy must choose
            sched.submit(Request(req_id=n, prompt=np.ones(4),
                                 max_new_tokens=2 + n % 3), 0)
        step = 0
        while not sched.drained():
            sched.admit(step, lambda r: True)
            for row, seq in list(sched.active.items()):
                seq.generated.append(0)
                if seq.done:
                    sched.evict(step, row)
            step += 1
        return sched.decision_log

    @pytest.mark.parametrize("policy", ["fifo", "random", "static"])
    def test_scheduler_deterministic(self, policy):
        assert self._run(policy) == self._run(policy)

    def test_seed_changes_random_policy(self):
        assert self._run("random", 0) != self._run("random", 1)

    def test_static_admits_only_empty_batch(self):
        sched = ContinuousScheduler(2, policy="static")
        for i in range(4):
            sched.submit(Request(req_id=i, prompt=np.ones(2),
                                 max_new_tokens=1), 0)
        assert len(sched.admit(0, lambda r: True)) == 2
        assert sched.admit(1, lambda r: True) == []   # batch occupied
        sched.evict(2, 0)
        assert sched.admit(3, lambda r: True) == []   # still one active
        sched.evict(3, 1)
        assert len(sched.admit(4, lambda r: True)) == 2

    def test_backpressure_stops_admission(self):
        sched = ContinuousScheduler(4)
        for i in range(3):
            sched.submit(Request(req_id=i, prompt=np.ones(2),
                                 max_new_tokens=1), 0)
        out = sched.admit(0, lambda r: r.req_id < 1)
        assert [s.req.req_id for s in out] == [0]
        assert sched.queue_depth() == 2


class TestSloController:
    def test_disabled_without_slo(self):
        c = SloController(None)
        c.record(100.0)
        assert c.update(0) is False and c.decisions == []

    def test_toggle_replay(self):
        lat = [1.0] * 20 + [9.0] * 30 + [1.0] * 40

        def replay():
            c = SloController(5.0, window=8, hysteresis=0.5,
                              dwell_steps=4)
            out = []
            for i, ms in enumerate(lat):
                c.record(ms)
                out.append(c.update(i))
            return c.decisions, out

        d1, states = replay()
        d2, _ = replay()
        assert d1 == d2                      # deterministic replay
        events = [e for _, e, _ in d1]
        assert events[:2] == ["spec_on", "spec_off"]
        assert states[25] is True and states[-1] is False

    def test_dwell_blocks_flapping(self):
        c = SloController(5.0, window=4, dwell_steps=100)
        for i, ms in enumerate([9, 9, 9, 1, 1, 1, 9, 9, 9, 1]):
            c.record(float(ms))
            c.update(i)
        assert len(c.decisions) <= 1

    def test_validation(self):
        with pytest.raises(HorovodTpuError):
            SloController(5.0, hysteresis=0.0)
        with pytest.raises(HorovodTpuError):
            SloController(5.0, window=0)


class TestInputValidation:
    """The satellite bugfix: impossible requests raise HorovodTpuError
    (InvalidRequestError also IS-A ValueError for older callers)."""

    def test_init_decode_cache_bad_batch(self, model):
        cfg, _ = model
        with pytest.raises(HorovodTpuError, match="batch"):
            init_decode_cache(cfg, 0, 8)

    def test_generate_bad_args(self, model):
        cfg, params = model
        prompt = jnp.ones((1, 4), jnp.int32)
        with pytest.raises(HorovodTpuError, match="max_new_tokens"):
            transformer_generate(params, cfg, prompt, 0)
        with pytest.raises(HorovodTpuError, match="max_len"):
            transformer_generate(params, cfg, prompt, 4, max_len=2)
        with pytest.raises(HorovodTpuError, match="non-empty"):
            transformer_generate(params, cfg,
                                 jnp.ones((1, 0), jnp.int32), 4)

    def test_prefill_prompt_longer_than_window(self, model):
        cfg, params = model
        cache = init_decode_cache(cfg, 1, 4)
        with pytest.raises(HorovodTpuError, match="max_len"):
            transformer_prefill(params, cache,
                                jnp.ones((1, 8), jnp.int32), cfg)

    def test_server_rejects_oversized_request(self, model):
        cfg, params = model
        srv = InferenceServer(params, cfg, max_seq_tokens=16,
                              max_batch=2, page_tokens=4)
        with pytest.raises(HorovodTpuError, match="budget"):
            srv.submit(np.ones(8, np.int32), 16)
        with pytest.raises(HorovodTpuError, match="policy"):
            InferenceServer(params, cfg, max_seq_tokens=16,
                            max_batch=2, policy="nope")


class TestInferenceServer:
    def test_continuous_matches_generate(self, model):
        """Every request served through the pooled continuous batch
        yields exactly transformer_generate's greedy tokens."""
        cfg, params = model
        srv = InferenceServer(params, cfg, max_seq_tokens=24,
                              max_batch=3, page_tokens=4)
        rng = np.random.RandomState(2)
        reqs = []
        for _ in range(7):
            prompt = rng.randint(0, 64, size=int(rng.choice([3, 5])))
            mn = int(rng.randint(2, 8))
            reqs.append((srv.submit(prompt, mn), prompt, mn))
        by_id = {s.req.req_id: s.generated for s in srv.run()}
        for rid, prompt, mn in reqs:
            ref, _ = transformer_generate(
                params, cfg, jnp.asarray(prompt[None], jnp.int32), mn)
            assert by_id[rid] == np.asarray(ref)[0].tolist()
        assert srv.pool.pages_free() == srv.pool.total_pages

    def test_spec_serving_matches_generate(self, model):
        """Speculative rounds (independent draft) stay greedy-exact."""
        cfg, params = model
        draft = transformer_init(jax.random.PRNGKey(9), cfg)
        srv = InferenceServer(params, cfg, max_seq_tokens=24,
                              max_batch=2, page_tokens=4,
                              draft_params=draft, draft_cfg=cfg,
                              gamma=3, force_spec=True)
        rng = np.random.RandomState(3)
        reqs = []
        for _ in range(4):
            prompt = rng.randint(0, 64, size=4)
            reqs.append((srv.submit(prompt, 6), prompt))
        by_id = {s.req.req_id: s.generated for s in srv.run()}
        assert srv.spec_steps > 0
        for rid, prompt in reqs:
            ref, _ = transformer_generate(
                params, cfg, jnp.asarray(prompt[None], jnp.int32), 6)
            assert by_id[rid] == np.asarray(ref)[0].tolist()

    def test_eos_stops_row(self, model):
        cfg, params = model
        prompt = np.arange(4, dtype=np.int32)
        ref, _ = transformer_generate(
            params, cfg, jnp.asarray(prompt[None]), 8)
        eos = int(np.asarray(ref)[0, 2])
        srv = InferenceServer(params, cfg, max_seq_tokens=16,
                              max_batch=2, page_tokens=4)
        srv.submit(prompt, 8, eos_id=eos)
        (seq,) = srv.run()
        assert seq.generated[-1] == eos and len(seq.generated) <= 3
        assert seq.generated == np.asarray(ref)[
            0, :len(seq.generated)].tolist()


class TestBenchRecords:
    def test_append_and_stale_gate(self, tmp_path, caplog):
        path = str(tmp_path / "BENCH_serve.json")
        assert read_latest_record(path) is None
        append_record(path, {"bench": "decode_bench", "x": 1})
        rec = read_latest_record(path)
        assert rec["x"] == 1 and rec["stale"] is False
        assert "captured_utc" in rec
        # age a record past the gate
        old = {"bench": "decode_bench", "x": 2,
               "captured_unix": time.time() - 100 * 3600}
        with open(path, "a") as f:
            f.write(json.dumps(old) + "\n")
        with caplog.at_level("WARNING"):
            rec = read_latest_record(path)
        assert rec["stale"] is True and rec["stale_hours"] > 24
        assert any("stale" in m for m in caplog.messages)

    def test_run_trace_stats(self, model):
        cfg, params = model
        trace = make_trace(3, 5, cfg.vocab_size, prompt_lens=(3, 5),
                           max_new_lo=2, max_new_hi=6,
                           arrival_every=1.0)
        srv = InferenceServer(params, cfg, max_seq_tokens=16,
                              max_batch=2, page_tokens=4)
        stats = run_trace(srv, trace)
        assert stats["tokens_out"] == sum(mn for _, _, mn in trace)
        assert 0 < stats["batch_occupancy_mean"] <= 1
        assert 0 < stats["kv_pool_peak_utilization"] <= 1
        assert stats["request_p99_ms"] >= stats["request_p50_ms"]

    def test_make_trace_deterministic_and_bimodal(self):
        t1 = make_trace(5, 20, 64, long_frac=0.5, long_lo=90,
                        long_hi=99)
        t2 = make_trace(5, 20, 64, long_frac=0.5, long_lo=90,
                        long_hi=99)
        assert all((a[0] == b[0] and a[2] == b[2]
                    and np.array_equal(a[1], b[1]))
                   for a, b in zip(t1, t2))
        assert any(mn >= 90 for _, _, mn in t1)
        assert any(mn < 90 for _, _, mn in t1)


@pytest.mark.slow
class TestReplicaElastic:
    """np=2-style e2e: two serving replicas over the rendezvous
    control plane; the serve.replica_die fault kills one mid-stream;
    the manager's lease/respawn recovers with no lost sequence and
    token-identical results."""

    CONFIG = {
        "cfg": dict(vocab_size=64, d_model=32, n_heads=4, d_head=8,
                    d_ff=64, n_layers=2, compute_dtype="float32"),
        "seed": 0,
        "serve": dict(max_seq_tokens=24, max_batch=2, page_tokens=4),
    }

    def _requests(self):
        rng = np.random.RandomState(1)
        return [(rng.randint(0, 64, size=4).tolist(),
                 int(rng.randint(2, 6))) for _ in range(6)]

    def _serve(self, child_env):
        from horovod_tpu.serve.replica import ReplicaManager
        env = {"JAX_PLATFORMS": "cpu"}
        env.update(child_env)
        with ReplicaManager(2, self.CONFIG, lease_ttl=10.0,
                            respawn_backoff=0.2,
                            child_env=env) as mgr:
            for prompt, mn in self._requests():
                mgr.submit(prompt, mn)
            results = mgr.wait_all(timeout=180)
            respawns = mgr._respawns
        return results, respawns

    def test_replica_death_recovers_all_sequences(self):
        baseline, r0 = self._serve({})
        assert r0 == 0
        assert len(baseline) == 6
        recovered, r1 = self._serve({
            "HOROVOD_FAULT_SPEC": "serve.replica_die@3:exit:1",
            "HOROVOD_FAULT_HOSTS": "replica1",
        })
        assert r1 >= 1                      # the dead replica respawned
        assert recovered == baseline        # no lost/garbled sequence
