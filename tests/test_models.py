"""Model zoo tests: shapes, param counts, train-mode stat updates, and a
distributed train step on ResNet/MNIST (reference analog: the example
configs in BASELINE.json exercised end-to-end)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.models import (
    mnist_cnn_apply,
    mnist_cnn_init,
    nll_loss,
    resnet_apply,
    resnet_init,
)


class TestResNet:
    def test_resnet50_param_count(self):
        v = resnet_init(jax.random.PRNGKey(0), 50, num_classes=1000)
        n = sum(p.size for p in jax.tree_util.tree_leaves(v["params"]))
        # torchvision resnet50: 25,557,032 params
        assert abs(n - 25_557_032) / 25_557_032 < 0.01

    @pytest.mark.parametrize("depth", [18, 50])
    def test_forward_shapes(self, depth):
        v = resnet_init(jax.random.PRNGKey(0), depth, num_classes=10)
        x = jnp.ones((2, 32, 32, 3))
        logits, new_stats = resnet_apply(v, x, train=True,
                                         compute_dtype=jnp.float32)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32
        # Train mode must update batch stats.
        old = v["batch_stats"]["bn_stem"]["mean"]
        new = new_stats["bn_stem"]["mean"]
        assert not np.allclose(np.asarray(old), np.asarray(new))

    def test_eval_mode_keeps_stats(self):
        v = resnet_init(jax.random.PRNGKey(0), 18, num_classes=10)
        x = jnp.ones((2, 32, 32, 3))
        _, new_stats = resnet_apply(v, x, train=False,
                                    compute_dtype=jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(v["batch_stats"]["bn_stem"]["mean"]),
            np.asarray(new_stats["bn_stem"]["mean"]),
        )

    def test_bf16_compute(self):
        v = resnet_init(jax.random.PRNGKey(0), 18, num_classes=10)
        x = jnp.ones((2, 32, 32, 3))
        logits, _ = resnet_apply(v, x, train=True,
                                 compute_dtype=jnp.bfloat16)
        assert logits.dtype == jnp.float32
        assert np.all(np.isfinite(np.asarray(logits)))


class TestMnist:
    def test_forward(self):
        p = mnist_cnn_init(jax.random.PRNGKey(0))
        lp = mnist_cnn_apply(p, jnp.ones((4, 28, 28, 1)))
        assert lp.shape == (4, 10)
        # log_softmax rows sum to 1 in prob space.
        np.testing.assert_allclose(
            np.exp(np.asarray(lp)).sum(-1), np.ones(4), rtol=1e-5)

    def test_train_step_converges(self):
        """A few SGD steps on a fixed batch must reduce the loss — the
        minimum end-to-end slice of BASELINE config 1."""
        params = mnist_cnn_init(jax.random.PRNGKey(0))
        opt = hvd.DistributedOptimizer(optax.sgd(0.05))
        opt_state = opt.init(params)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 28, 28, 1))
        y = jnp.arange(8) % 10

        def loss_fn(p):
            return nll_loss(mnist_cnn_apply(p, x), y)

        losses = []
        for _ in range(5):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestDistributedResNetStep:
    def test_spmd_train_step(self):
        """One compiled SPMD train step over the 8-device mesh with
        sync batch-norm and in-graph gradient allreduce (the money path,
        SURVEY.md §3.3, on a tiny ResNet-18)."""
        v = resnet_init(jax.random.PRNGKey(0), 18, num_classes=10)
        params = {"params": v["params"], "batch_stats": v["batch_stats"]}
        cfg = v["config"]
        opt = optax.sgd(0.01)
        opt_state = opt.init(params["params"])
        batch = (
            jax.random.normal(jax.random.PRNGKey(1), (16, 32, 32, 3)),
            jnp.arange(16) % 10,
        )

        def step(state, opt_state, batch):
            x, y = batch

            def loss_fn(p):
                logits, ns = resnet_apply(
                    {"params": p, "batch_stats": state["batch_stats"],
                     "config": cfg},
                    x, train=True, compute_dtype=jnp.float32,
                    axis_name=hvd.GLOBAL_AXIS)
                onehot = jax.nn.one_hot(y, 10)
                loss = -jnp.mean(
                    jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
                return loss, ns

            (loss, ns), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state["params"])
            grads = hvd.allreduce(grads)  # in-jit → pmean over the axis
            updates, new_opt = opt.update(grads, opt_state, state["params"])
            new_params = optax.apply_updates(state["params"], updates)
            return ({"params": new_params, "batch_stats": ns}, new_opt,
                    hvd.allreduce(loss))

        # Snapshot before the call: params are donated (freed) by the step.
        stem_old = np.asarray(params["params"]["stem"]["kernel"])
        compiled = hvd.data_parallel(step)
        (new_state, new_opt, loss) = compiled(params, opt_state, batch)
        assert np.isfinite(float(loss))

        def _leaf(t):
            return np.asarray(t["params"]["stem"]["kernel"])

        assert not np.allclose(stem_old, _leaf(new_state))


class TestZooModels:
    """VGG-16 / Inception V3 — the reference's other published scaling
    table rows (docs/benchmarks.rst, SURVEY.md §6)."""

    def test_zoo_dispatch_and_names(self):
        from horovod_tpu.models import zoo_apply, zoo_init, zoo_models

        names = zoo_models()
        assert {"resnet50", "resnet101", "vgg16", "inception3"} <= set(names)
        with pytest.raises(ValueError, match="unknown model"):
            zoo_init("alexnet", jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="unknown model"):
            zoo_apply("alexnet")

    def test_vgg16_canonical_param_count(self):
        from horovod_tpu.models import zoo_init

        v = zoo_init("vgg16", jax.random.PRNGKey(0))
        n = sum(x.size for x in jax.tree_util.tree_leaves(v["params"]))
        assert n == 138_357_544  # torchvision/tf_cnn_benchmarks vgg16

    def test_vgg16_forward_small(self):
        from horovod_tpu.models import zoo_apply, zoo_init

        v = zoo_init("vgg16", jax.random.PRNGKey(0), num_classes=10,
                     image_size=32)
        logits, ns = zoo_apply("vgg16")(
            v, jnp.ones((2, 32, 32, 3)), train=True)
        assert logits.shape == (2, 10) and logits.dtype == jnp.float32
        assert ns == {}

    def test_vgg16_bad_image_size(self):
        from horovod_tpu.models import zoo_init

        with pytest.raises(ValueError, match="image_size"):
            zoo_init("vgg16", jax.random.PRNGKey(0), image_size=100)

    def test_inception3_canonical_param_count(self):
        from horovod_tpu.models import zoo_init

        v = zoo_init("inception3", jax.random.PRNGKey(0))
        n = sum(x.size for x in jax.tree_util.tree_leaves(v["params"]))
        assert n == 23_834_568  # tf.slim inception_v3 (no aux head)

    def test_inception3_forward_min_size_and_stats(self):
        from horovod_tpu.models import zoo_apply, zoo_init

        v = zoo_init("inception3", jax.random.PRNGKey(0), num_classes=10)
        logits, ns = zoo_apply("inception3")(
            v, jnp.ones((1, 75, 75, 3)), train=True)
        assert logits.shape == (1, 10)
        # every conv-bn unit reports updated stats
        assert set(ns) == set(v["batch_stats"])

    def test_vgg16_train_step_updates(self):
        from horovod_tpu.models import zoo_apply, zoo_init

        v = zoo_init("vgg16", jax.random.PRNGKey(0), num_classes=10,
                     image_size=32)
        apply = zoo_apply("vgg16")

        def loss_fn(p):
            logits, _ = apply({"params": p, "batch_stats": {},
                               "config": v["config"]},
                              jnp.ones((2, 32, 32, 3)), train=True,
                              compute_dtype=jnp.float32)
            return -jnp.mean(jax.nn.log_softmax(logits)[:, 0])

        g = jax.grad(loss_fn)(v["params"])
        gn = sum(float(jnp.abs(x).sum())
                 for x in jax.tree_util.tree_leaves(g))
        assert np.isfinite(gn) and gn > 0


class TestSpaceToDepthStem:
    """Conv0 space-to-depth (HOROVOD_CONV0_SPACE_TO_DEPTH) must be
    numerically equivalent to the plain 7x7/s2 SAME stem — same weights,
    re-tiled in-graph."""

    def test_stem_transform_matches_plain_conv(self):
        from horovod_tpu.models import layers as L
        from horovod_tpu.models.resnet import _stem_space_to_depth_apply

        p = L.conv2d_init(jax.random.PRNGKey(0), 3, 64, 7, jnp.float32)
        for hw in (64, 224):
            x = jax.random.normal(jax.random.PRNGKey(1), (2, hw, hw, 3))
            ref = L.conv2d_apply(p, x, 2, compute_dtype=None)
            got = _stem_space_to_depth_apply(p, x, None)
            assert got.shape == ref.shape
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       atol=1e-5, rtol=1e-5)

    def test_full_apply_matches_with_flag(self, monkeypatch):
        from horovod_tpu.models import resnet_init, resnet_apply

        v = resnet_init(jax.random.PRNGKey(0), 18, num_classes=10)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        base, _ = resnet_apply(v, x, train=False, compute_dtype=None)
        monkeypatch.setenv("HOROVOD_CONV0_SPACE_TO_DEPTH", "1")
        s2d, _ = resnet_apply(v, x, train=False, compute_dtype=None)
        np.testing.assert_allclose(np.asarray(s2d), np.asarray(base),
                                   atol=1e-4, rtol=1e-4)

    def test_odd_spatial_falls_back(self, monkeypatch):
        from horovod_tpu.models import resnet_init, resnet_apply

        monkeypatch.setenv("HOROVOD_CONV0_SPACE_TO_DEPTH", "1")
        v = resnet_init(jax.random.PRNGKey(0), 18, num_classes=10)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 33, 33, 3))
        logits, _ = resnet_apply(v, x, train=False, compute_dtype=None)
        assert logits.shape == (1, 10)


class TestTransformerGQAWindow:
    """GQA/MQA and sliding-window configs on the flagship transformer
    (kernel features wired through the model family)."""

    def _cfg(self, **kw):
        from horovod_tpu.models import TransformerConfig

        base = dict(vocab_size=128, d_model=64, n_heads=4, d_head=16,
                    d_ff=128, n_layers=2, compute_dtype=jnp.float32)
        base.update(kw)
        return TransformerConfig(**base)

    def test_gqa_param_shapes_and_loss(self):
        from horovod_tpu.models import (
            transformer_init, transformer_ref_loss)

        cfg = self._cfg(n_kv_heads=2)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        assert params["blocks"]["wq"].shape == (2, 64, 4, 16)
        assert params["blocks"]["wk"].shape == (2, 64, 2, 16)
        assert params["blocks"]["wv"].shape == (2, 64, 2, 16)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
        loss = transformer_ref_loss(params, toks[:, :-1], toks[:, 1:], cfg)
        assert bool(jnp.isfinite(loss))
        g = jax.grad(lambda p: transformer_ref_loss(
            p, toks[:, :-1], toks[:, 1:], cfg))(params)
        assert bool(jnp.isfinite(g["blocks"]["wk"]).all())

    def test_window_changes_logits(self):
        from horovod_tpu.models import (
            transformer_init, transformer_ref_apply)

        cfg_full = self._cfg()
        cfg_win = self._cfg(attn_window=4)
        params = transformer_init(jax.random.PRNGKey(0), cfg_full)
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, 128)
        lf, _ = transformer_ref_apply(params, toks, cfg_full)
        lw, _ = transformer_ref_apply(params, toks, cfg_win)
        # Early positions (< window) see identical context; late ones
        # differ because the window hides distant tokens.
        np.testing.assert_allclose(lf[:, :4], lw[:, :4], atol=1e-5)
        assert not np.allclose(lf[:, -1], lw[:, -1])

    def test_gqa_under_sp_mesh_matches_dense_heads(self):
        # The sp path repeats kv heads; loss must equal the explicit
        # MHA model with the same repeated weights.
        from jax.sharding import Mesh

        from horovod_tpu.models import (
            transformer_init, transformer_ref_loss)

        devs = np.array(jax.devices()[:2])
        if len(devs) < 2:
            pytest.skip("needs 2 virtual devices")
        cfg = self._cfg(n_kv_heads=2, attn_impl="ring")
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 128)
        x, y = toks[:, :-1], toks[:, 1:]
        ref = transformer_ref_loss(params, x, y, cfg)

        from horovod_tpu.models.transformer import (
            _loss_shard)
        from jax.sharding import PartitionSpec as P
        from jax import shard_map
        mesh = Mesh(devs, ("sp",))
        import functools
        f = jax.jit(shard_map(
            functools.partial(_loss_shard, cfg=cfg, axes={"sp": True},
                              n_microbatches=1),
            mesh=mesh,
            in_specs=(P(), P(None, "sp"), P(None, "sp")),
            out_specs=P(), check_vma=False))
        got = f(params, x, y)
        np.testing.assert_allclose(float(got), float(ref), rtol=2e-4)

    def test_config_validation(self):
        from horovod_tpu.models import TransformerConfig

        with pytest.raises(ValueError, match="attn_window"):
            self._cfg(attn_window=-1)
        with pytest.raises(ValueError, match="n_kv_heads"):
            self._cfg(n_kv_heads=3)   # 4 heads % 3 != 0
        assert TransformerConfig(n_heads=4, n_kv_heads=2).kv_heads == 2
