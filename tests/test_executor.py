"""Programmatic executor tests (reference: test/single/test_ray.py's
RayExecutor semantics — persistent pool, repeated run(), per-rank
results, failure surfacing — on localhost processes).
"""

import os

import pytest

from horovod_tpu.runner.executor import Executor
from horovod_tpu.common.exceptions import HorovodTpuError


def fn_rank():
    return int(os.environ["HOROVOD_RANK"])


def fn_add(a, b=0):
    return a + b + int(os.environ["HOROVOD_RANK"])


def fn_fail():
    raise RuntimeError("boom from worker")


def fn_collective():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = hvd.allreduce(np.full((2,), float(hvd.rank() + 1)), average=False)
    return [float(v) for v in np.asarray(out)]


@pytest.fixture()
def clean_env(monkeypatch):
    # Workers must see one CPU device each, not the sim's 8.
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")


@pytest.mark.integration
class TestExecutor:
    def test_pool_reuse_and_rank_results(self, clean_env):
        with Executor(np=2) as ex:
            assert ex.run(fn_rank) == [0, 1]
            # Same pool, second dispatch — no relaunch.
            assert ex.run(fn_add, args=(10,), kwargs={"b": 5}) == [15, 16]
            assert ex.run(fn_rank) == [0, 1]

    def test_worker_exception_surfaces_and_pool_survives(self, clean_env):
        with Executor(np=2) as ex:
            with pytest.raises(HorovodTpuError, match="boom from worker"):
                ex.run(fn_fail)
            # The pool stays alive after a failed command (reference:
            # actors survive task exceptions).
            assert ex.run(fn_rank) == [0, 1]

    def test_run_remote_then_get(self, clean_env):
        with Executor(np=2) as ex:
            t1 = ex.run_remote(fn_rank)
            t2 = ex.run_remote(fn_add, args=(1,))
            assert ex.get(t2) == [1, 2]
            assert ex.get(t1) == [0, 1]

    def test_cross_process_collective_through_pool(self, clean_env):
        with Executor(np=2) as ex:
            out = ex.run(fn_collective, timeout=240)
        # sum of (1,2) over 2 ranks = 3 on both.
        assert out == [[3.0, 3.0], [3.0, 3.0]]

    def test_not_started_raises(self):
        ex = Executor(np=2)
        with pytest.raises(HorovodTpuError, match="not started"):
            ex.run(fn_rank)


class TestRayAdapter:
    def test_assign_ranks_groups_by_host(self):
        from horovod_tpu.ray import assign_ranks

        envs = assign_ranks(["a", "b", "a", "b"])
        assert [e["HOROVOD_RANK"] for e in envs] == [0, 1, 2, 3]
        assert [e["HOROVOD_LOCAL_RANK"] for e in envs] == [0, 0, 1, 1]
        assert [e["HOROVOD_CROSS_RANK"] for e in envs] == [0, 1, 0, 1]
        assert all(e["HOROVOD_LOCAL_SIZE"] == 2 for e in envs)
        assert all(e["HOROVOD_CROSS_SIZE"] == 2 for e in envs)

    @pytest.mark.integration
    def test_ray_executor_falls_back_to_local_pool(self, monkeypatch):
        from horovod_tpu.ray import RayExecutor, ray_available

        if ray_available():  # pragma: no cover — ray not in base image
            pytest.skip("ray installed; fallback path not in use")
        monkeypatch.delenv("XLA_FLAGS", raising=False)
        ex = RayExecutor(num_workers=2)
        ex.start()
        try:
            assert ex.run(fn_rank) == [0, 1]
        finally:
            ex.shutdown()


def fn_elastic_rank():
    return int(os.environ["HOROVOD_RANK"])


@pytest.mark.integration
class TestElasticExecutor:
    def test_run_returns_results(self, tmp_path, clean_env):
        from horovod_tpu.runner.executor import ElasticExecutor

        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("localhost:2\n")
        script = tmp_path / "discover.sh"
        script.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
        script.chmod(0o755)

        ex = ElasticExecutor(str(script), min_np=2, slots=2)
        results = ex.run(fn_elastic_rank)
        assert sorted(results) == [0, 1]


@pytest.mark.integration
def test_main_defined_classes_roundtrip(clean_env):
    """Functions AND classes defined in the driver's __main__ script
    must ship to workers and results return (multiprocessing-spawn
    module aliasing in the worker loop)."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [os.sys.executable,
         os.path.join(repo, "tests", "data", "executor_main_cls.py")],
        capture_output=True, text=True, timeout=240, env=env, cwd=repo)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "MAIN_CLASS_ROUNDTRIP_OK" in r.stdout
