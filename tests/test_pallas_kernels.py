"""Pallas Adasum-kernel numerics under the interpreter (reference:
adasum.h DispatchComputeDotAndNormSqrds / DispatchScaledAdd inner
loops; the interpreter runs the identical kernel code the TPU compiles).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import pallas_kernels as PK
from horovod_tpu.ops.adasum import adasum_reference


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    monkeypatch.setenv("HOROVOD_PALLAS_INTERPRET", "1")


@pytest.mark.parametrize("n", [128 * 256, 128 * 256 + 1, 1000, 7])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_dot_norms_matches_jnp(n, dtype):
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(2, n), dtype)
    b = jnp.asarray(rng.randn(2, n), dtype)
    out = PK.fused_dot_norms(a, b)
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    expect = jnp.stack([
        jnp.sum(af * bf, -1), jnp.sum(af * af, -1), jnp.sum(bf * bf, -1)
    ], -1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-5, atol=1e-4)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_scaled_add(dtype):
    rng = np.random.RandomState(1)
    a = jnp.asarray(rng.randn(3, 500), dtype)
    b = jnp.asarray(rng.randn(3, 500), dtype)
    ca = jnp.asarray([0.5, 1.0, -2.0], jnp.float32)
    cb = jnp.asarray([1.5, 0.0, 3.0], jnp.float32)
    out = PK.fused_scaled_add(ca, cb, a, b)
    expect = (ca[:, None] * a.astype(jnp.float32)
              + cb[:, None] * b.astype(jnp.float32)).astype(dtype)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expect, np.float32),
        rtol=2e-2 if dtype == jnp.bfloat16 else 1e-6, atol=1e-4)
    assert out.dtype == dtype


def test_pair_combine_matches_reference():
    rng = np.random.RandomState(2)
    a = rng.randn(2, 300).astype(np.float32)
    b = rng.randn(2, 300).astype(np.float32)
    out = PK.pallas_pair_combine_batched(jnp.asarray(a), jnp.asarray(b))
    for i in range(2):
        expect = adasum_reference([a[i], b[i]])
        np.testing.assert_allclose(np.asarray(out[i]), expect, rtol=1e-4)


def test_pair_combine_zero_norm_guard():
    a = jnp.zeros((1, 64), jnp.float32)
    b = jnp.ones((1, 64), jnp.float32)
    out = PK.pallas_pair_combine_batched(a, b)
    # Zero-norm side contributes via the guard coefficient 1.0: result = b.
    np.testing.assert_allclose(np.asarray(out), np.ones((1, 64)))


def test_tree_reduce_uses_pallas_when_forced(monkeypatch):
    # Force the pallas path (normally auto-off on CPU) through the full
    # Adasum tree; numerics must match the float64 reference model.
    monkeypatch.setenv("HOROVOD_ADASUM_PALLAS", "1")
    from horovod_tpu.ops.adasum import adasum_tree_reduce

    rng = np.random.RandomState(3)
    grads = rng.randn(8, 129).astype(np.float32)
    out = adasum_tree_reduce(jnp.asarray(grads))
    expect = adasum_reference(list(grads))
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4)


def test_auto_gating():
    # CPU interpreter default: off unless forced.
    assert not PK.pallas_enabled(10**9)


# ---------------------------------------------------------------------------
# Fused-pipeline Pallas matmul (ops/fused_collectives.py)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shape", [(128, 128, 128), (200, 300, 150),
                                   (7, 5, 3), (129, 257, 129)])
def test_pallas_matmul_matches_jnp(shape):
    from horovod_tpu.ops.fused_collectives import pallas_matmul
    m, k, n = shape
    rng = np.random.RandomState(7)
    a = rng.randn(m, k).astype(np.float32)
    b = rng.randn(k, n).astype(np.float32)
    out = pallas_matmul(jnp.asarray(a), jnp.asarray(b))
    assert out.shape == (m, n)
    np.testing.assert_allclose(np.asarray(out), a @ b,
                               rtol=1e-4, atol=1e-3)


def test_pallas_matmul_shape_mismatch_raises():
    from horovod_tpu.common.exceptions import HorovodTpuError
    from horovod_tpu.ops.fused_collectives import pallas_matmul
    with pytest.raises(HorovodTpuError, match="inner dims"):
        pallas_matmul(jnp.zeros((4, 5)), jnp.zeros((6, 7)))


def test_fused_pallas_gating(monkeypatch):
    from horovod_tpu.ops import fused_collectives as fc
    # Opt-in: off by default even for big operands.
    monkeypatch.delenv("HOROVOD_FUSED_PALLAS", raising=False)
    assert not fc.fused_pallas_enabled(10**9)
    # Tiny operands stay on the XLA dot even when forced.
    monkeypatch.setenv("HOROVOD_FUSED_PALLAS", "1")
    assert not fc.fused_pallas_enabled(16)
    if fc.PALLAS_AVAILABLE:
        assert fc.fused_pallas_enabled(10**9)


def test_chunk_matmul_rides_pallas_when_forced(monkeypatch):
    # The fused chunks' compute stage must route through the Pallas
    # kernel when HOROVOD_FUSED_PALLAS=1 and still match the XLA dot.
    monkeypatch.setenv("HOROVOD_FUSED_PALLAS", "1")
    from horovod_tpu.ops.fused_collectives import _chunk_matmul
    rng = np.random.RandomState(8)
    a = rng.randn(150, 140).astype(np.float32)
    b = rng.randn(140, 130).astype(np.float32)
    out = _chunk_matmul(jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), a @ b,
                               rtol=1e-4, atol=1e-3)
