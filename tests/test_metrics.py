"""Metrics subsystem: registry primitives, Prometheus exposition + the
scrape endpoint, hot-path instrumentation consistency, KV fleet
aggregation, the merged-view CLI, and the catalog lint.
"""

import json
import math
import os
import subprocess
import sys
import time
import urllib.request

import jax.numpy as jnp
import pytest

import horovod_tpu as hvd
from horovod_tpu.metrics import catalog as met_catalog
from horovod_tpu.metrics import exposition, fleet
from horovod_tpu.metrics.__main__ import _parse_prometheus
from horovod_tpu.metrics.registry import (
    Counter, Histogram, MetricsRegistry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Registry primitives
# ---------------------------------------------------------------------------

def test_counter_basics():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "help", ("kind",))
    c.labels("a").inc()
    c.labels("a").inc(2.5)
    c.labels("b").inc()
    assert c.labels("a").get() == 3.5
    assert c.labels("b").get() == 1.0
    with pytest.raises(ValueError):
        c.labels("a").inc(-1)


def test_unlabeled_convenience():
    reg = MetricsRegistry()
    c = reg.counter("plain_total", "help")
    c.inc()
    c.inc(4)
    assert c._solo().get() == 5.0
    g = reg.gauge("g", "help")
    g.set(7)
    g.inc()
    assert g._solo().get() == 8.0


def test_labels_interning_and_kwargs():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "h", ("kind", "dtype"))
    assert c.labels("x", "f32") is c.labels("x", "f32")
    assert c.labels("x", "f32") is c.labels(kind="x", dtype="f32")
    c2 = reg.counter("u_total", "h", ("kind", "bits"))
    assert c2.labels("x", 32) is c2.labels("x", "32")  # str-coerced
    with pytest.raises(ValueError):
        c.labels("only-one")


def test_reregistration_type_mismatch_raises():
    reg = MetricsRegistry()
    reg.counter("m", "h", ("a",))
    assert reg.counter("m", "h", ("a",)) is reg.get("m")  # idempotent
    with pytest.raises(ValueError):
        reg.gauge("m", "h", ("a",))
    with pytest.raises(ValueError):
        reg.counter("m", "h", ("b",))


def test_histogram_buckets_cumulative():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "h", buckets=[0.01, 0.1, 1.0])
    for v in (0.005, 0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    child = h._solo()
    cum = child.cumulative()
    assert cum == [(0.01, 2), (0.1, 3), (1.0, 4), (math.inf, 5)]
    assert child.count == 5
    assert abs(child.sum - 5.56) < 1e-9


def test_default_latency_buckets_span():
    from horovod_tpu.metrics.registry import default_latency_buckets
    b = default_latency_buckets()
    assert b[0] == 1e-6 and b[-1] > 60
    assert all(x < y for x, y in zip(b, b[1:]))


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------

def test_render_text_format():
    reg = MetricsRegistry()
    c = reg.counter("hvd_x_total", "calls with \"quotes\"", ("kind",))
    c.labels("AR").inc(3)
    h = reg.histogram("hvd_l_seconds", "lat", ("kind",), buckets=[0.1, 1.0])
    h.labels("AR").observe(0.05)
    text = exposition.render(reg)
    assert '# HELP hvd_x_total calls with \\"quotes\\"' in text
    assert "# TYPE hvd_x_total counter" in text
    assert 'hvd_x_total{kind="AR"} 3' in text
    assert "# TYPE hvd_l_seconds histogram" in text
    assert 'hvd_l_seconds_bucket{kind="AR",le="0.1"} 1' in text
    assert 'hvd_l_seconds_bucket{kind="AR",le="+Inf"} 1' in text
    assert 'hvd_l_seconds_sum{kind="AR"} 0.05' in text
    assert 'hvd_l_seconds_count{kind="AR"} 1' in text


def test_render_parses_back():
    reg = MetricsRegistry()
    reg.counter("hvd_a_total", "h", ("k",)).labels("x").inc(2)
    reg.histogram("hvd_b_seconds", "h", buckets=[1.0])._solo().observe(0.5)
    snap = _parse_prometheus(exposition.render(reg), rank=0)
    assert snap["metrics"]["hvd_a_total"]["samples"] == [[["x"], 2.0]]
    hist = snap["metrics"]["hvd_b_seconds"]
    assert hist["kind"] == "histogram"
    [[_, acc]] = hist["samples"]
    assert acc["count"] == 1 and acc["sum"] == 0.5


# ---------------------------------------------------------------------------
# Hot-path instrumentation + the scrape endpoint (the acceptance smoke
# test: N eager allreduces must be visible in a real HTTP scrape)
# ---------------------------------------------------------------------------

def _scrape(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        return r.read().decode()


def _sum_series(text, name, **label_filter):
    snap = _parse_prometheus(text, rank=0)
    m = snap["metrics"].get(name)
    if m is None:
        return 0.0
    names = m["labelnames"]
    total = 0.0
    for values, val in m["samples"]:
        labels = dict(zip(sorted(names), values))
        if all(labels.get(k) == v for k, v in label_filter.items()):
            total += val if not isinstance(val, dict) else val["count"]
    return total


def test_eager_allreduce_visible_in_scrape():
    n = 3
    port = exposition.start_server(0, addr="127.0.0.1")
    try:
        before = _scrape(port)
        for _ in range(n):
            hvd.allreduce(jnp.ones((16,), jnp.float32), name="m.smoke")
        after = _scrape(port)
    finally:
        exposition.stop_server()

    def delta(name, **f):
        return _sum_series(after, name, **f) - _sum_series(
            before, name, **f)

    assert delta("hvd_collective_calls_total", kind="ALLREDUCE") == n
    # 16 f32 * 8 ranks staged globally, n times.
    assert delta("hvd_collective_bytes_total", kind="ALLREDUCE") \
        == n * 16 * 4 * hvd.size()
    # Histogram observed once per call (count via the _count series).
    assert delta("hvd_collective_latency_seconds",
                 kind="ALLREDUCE") == n
    # Same shape n times: every dispatch is a cache hit or miss, and
    # they account for exactly the n calls.
    cache = delta("hvd_compile_cache_hits_total", kind="allreduce") + \
        delta("hvd_compile_cache_misses_total", kind="allreduce")
    assert cache == n
    for needle in ("hvd_collective_calls_total",
                   "hvd_collective_bytes_total",
                   "hvd_collective_latency_seconds_bucket",
                   "hvd_compile_cache_hits_total",
                   "hvd_compile_cache_misses_total"):
        assert needle in after


def test_metrics_disable_gates_hot_path():
    met_catalog.set_enabled(False)
    try:
        before = met_catalog.collective_calls.labels(
            "ALLREDUCE", "float32", "0").get()
        hvd.allreduce(jnp.ones((4,), jnp.float32), name="m.disabled")
        after = met_catalog.collective_calls.labels(
            "ALLREDUCE", "float32", "0").get()
        assert after == before
    finally:
        met_catalog.set_enabled(True)


def test_healthz_and_404():
    port = exposition.start_server(0, addr="127.0.0.1")
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert r.read() == b"ok\n"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        exposition.stop_server()


def test_steps_counter_increments():
    before = met_catalog.steps._solo().get()
    step = hvd.data_parallel(lambda x: x * 2, batch_args=(0,),
                             donate_args=())
    step(jnp.ones((8, 2)))
    step(jnp.ones((8, 2)))
    assert met_catalog.steps._solo().get() == before + 2


def test_grad_bytes_eager_counter():
    grads = {"w": jnp.ones((32,), jnp.float32),
             "b": jnp.ones((4,), jnp.float32)}
    before = met_catalog.grad_bytes_reduced._solo().get()
    hvd.allreduce_gradients(grads)
    assert met_catalog.grad_bytes_reduced._solo().get() \
        == before + (32 + 4) * 4


# ---------------------------------------------------------------------------
# Fleet snapshots / aggregation / CLI
# ---------------------------------------------------------------------------

def _mini_snap(rank, steps, calls_val):
    return {
        "rank": rank, "ts": time.time(),
        "metrics": {
            "hvd_steps_total": {
                "kind": "counter", "labelnames": [],
                "samples": [[[], float(steps)]]},
            "hvd_collective_calls_total": {
                "kind": "counter",
                "labelnames": ["kind", "dtype", "process_set"],
                "samples": [[["ALLREDUCE", "float32", "0"],
                             float(calls_val)]]},
        },
    }


def test_aggregate_sums_counters_keeps_gauges():
    s0 = _mini_snap(0, steps=10, calls_val=5)
    s1 = _mini_snap(1, steps=12, calls_val=7)
    for s, g in ((s0, 1.0), (s1, 3.0)):
        s["metrics"]["hvd_grad_bytes_per_step"] = {
            "kind": "gauge", "labelnames": [], "samples": [[[], g]]}
    agg = fleet.aggregate([s0, s1])
    assert agg["hvd_steps_total"]["samples"][()] == 22.0
    assert agg["hvd_collective_calls_total"]["samples"][
        ("ALLREDUCE", "float32", "0")] == 12.0
    assert agg["hvd_grad_bytes_per_step"]["samples"][()] == {0: 1.0, 1: 3.0}


def test_render_fleet_reports_skew():
    out = fleet.render_fleet([_mini_snap(0, 10, 5), _mini_snap(1, 14, 5)])
    assert "2 rank(s)" in out
    assert "step skew (max-min): 4" in out
    assert "collective calls: 10" in out


def test_snapshot_roundtrips_through_json():
    snap = fleet.snapshot(rank=3)
    again = json.loads(json.dumps(snap))
    assert again["rank"] == 3
    # Histogram samples carry mergeable buckets, not raw observations.
    lat = again["metrics"].get("hvd_collective_latency_seconds")
    if lat is not None:
        for _values, acc in lat["samples"]:
            assert set(acc) == {"sum", "count", "buckets", "inf"}


_PUBLISH_RANK1 = """
import os, sys
sys.path.insert(0, {repo!r})
import horovod_tpu  # noqa: F401  (registers the catalog)
from horovod_tpu.metrics import catalog, fleet
from horovod_tpu.runner.rendezvous import RendezvousClient
catalog.steps.inc(7)
catalog.collective_calls.labels("ALLREDUCE", "float32", "0").inc(2)
client = RendezvousClient("127.0.0.1", int(sys.argv[1]), sys.argv[2])
fleet.publish(client, rank=1)
"""


@pytest.mark.integration
def test_fleet_cli_merges_multirank_kv(tmp_path):
    """Acceptance: `python -m horovod_tpu.metrics` renders a merged
    multi-rank view from the KV, the snapshots coming from two distinct
    processes (this one as rank 0, a subprocess as rank 1)."""
    from horovod_tpu.runner.rendezvous import (
        RendezvousClient, RendezvousServer)

    srv = RendezvousServer(prefer_native=False)
    port = srv.start(0)
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "HOROVOD_RENDEZVOUS_ADDR": "127.0.0.1",
           "HOROVOD_RENDEZVOUS_PORT": str(port),
           "HOROVOD_SECRET_KEY": srv.secret}
    try:
        met_catalog.steps.inc(5)  # make rank 0 visibly non-empty
        fleet.publish(RendezvousClient("127.0.0.1", port, srv.secret),
                      rank=0)
        subprocess.run(
            [sys.executable, "-c",
             _PUBLISH_RANK1.format(repo=REPO), str(port), srv.secret],
            check=True, timeout=300, env=env, cwd=str(tmp_path))
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.metrics"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr
        out = proc.stdout
        assert "fleet view: 2 rank(s)" in out
        assert "step skew" in out
        # Rank rows for both ranks, in order.
        assert out.index("\n   0 ") < out.index("\n   1 ")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Catalog lint (code <-> docs drift)
# ---------------------------------------------------------------------------

def test_catalog_lint_passes_on_repo():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_metrics_catalog.py"), REPO],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_catalog_lint_catches_drift(tmp_path):
    cat_dir = tmp_path / "horovod_tpu" / "metrics"
    cat_dir.mkdir(parents=True)
    src = open(os.path.join(
        REPO, "horovod_tpu", "metrics", "catalog.py")).read()
    (cat_dir / "catalog.py").write_text(
        src + '\nghost = _REG.counter(\n    "hvd_ghost_total", "boo")\n')
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "METRICS.md").write_text(
        open(os.path.join(REPO, "docs", "METRICS.md")).read())
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_metrics_catalog.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "hvd_ghost_total" in proc.stdout
