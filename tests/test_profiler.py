"""merge_traces clock alignment via TRACE_START_MARKER (complements the
basic merge tests in test_aux.py: exact shift arithmetic, unaligned
fallback, truncated-trace tolerance, uncompressed device input).
"""

import gzip
import json

from horovod_tpu.utils import profiler as prof
from horovod_tpu.utils import timeline as tl_mod


def _write_host(tmp_path, events):
    """Write a Chrome-array host timeline directly (known timestamps —
    Timeline's perf_counter clock would make exact assertions flaky)."""
    f = tmp_path / "host.json"
    f.write_text("[\n" + ",\n".join(json.dumps(e) for e in events) + "\n]\n")
    return str(f)


def _write_dev(tmp_path, events, compress=True):
    payload = {"traceEvents": events}
    if compress:
        f = tmp_path / "dev.trace.json.gz"
        with gzip.open(f, "wt") as fh:
            json.dump(payload, fh)
    else:
        f = tmp_path / "dev.trace.json"
        f.write_text(json.dumps(payload))
    return str(f)


def test_marker_shift_is_exact(tmp_path):
    """Every host event must be shifted by exactly -marker_ts so the
    marker lands at t=0 on the device clock."""
    host = _write_host(tmp_path, [
        {"name": "before", "ph": "i", "ts": 100.0, "pid": 0, "tid": "t"},
        {"name": prof.TRACE_START_MARKER, "ph": "i", "ts": 250.0,
         "pid": 0, "tid": "profiler"},
        {"name": "EXECUTE", "ph": "X", "ts": 400.0, "dur": 25.0,
         "pid": 0, "tid": "grad.w"},
    ])
    dev = _write_dev(tmp_path, [
        {"name": "fusion.7", "ph": "X", "ts": 5.0, "dur": 10.0,
         "pid": 1, "tid": 2},
    ])
    out = tmp_path / "merged.json"
    stats = prof.merge_traces(host, dev, str(out))
    assert stats == {"device_events": 1, "host_events": 3,
                     "aligned": True, "out": str(out)}
    merged = json.load(open(out))["traceEvents"]
    by_name = {e["name"]: e for e in merged if "name" in e}
    assert by_name[prof.TRACE_START_MARKER]["ts"] == 0.0
    assert by_name["before"]["ts"] == -150.0   # 100 - 250
    assert by_name["EXECUTE"]["ts"] == 150.0   # 400 - 250
    assert by_name["EXECUTE"]["dur"] == 25.0   # durations untouched
    # Device events keep their own clock.
    assert by_name["fusion.7"]["ts"] == 5.0
    # Host pids offset out of the device pid space + labeled.
    assert by_name["EXECUTE"]["pid"] == prof.HOST_PID_OFFSET
    labels = [e for e in merged if e.get("ph") == "M"]
    assert any("control plane" in e["args"]["name"] for e in labels)


def test_no_marker_means_no_shift(tmp_path):
    host = _write_host(tmp_path, [
        {"name": "EXECUTE", "ph": "X", "ts": 400.0, "dur": 25.0,
         "pid": 2, "tid": "g"},
    ])
    dev = _write_dev(tmp_path, [])
    stats = prof.merge_traces(host, dev, str(tmp_path / "m.json"))
    assert not stats["aligned"]
    merged = json.load(open(tmp_path / "m.json"))["traceEvents"]
    ev = next(e for e in merged if e.get("name") == "EXECUTE")
    assert ev["ts"] == 400.0  # unshifted
    assert ev["pid"] == prof.HOST_PID_OFFSET + 2


def test_truncated_host_trace_tolerated(tmp_path):
    """A process that died mid-run leaves no closing bracket; the merge
    must still read every complete record."""
    f = tmp_path / "host.json"
    rec = {"name": prof.TRACE_START_MARKER, "ph": "i", "ts": 10.0,
           "pid": 0, "tid": "p"}
    f.write_text("[\n" + json.dumps(rec))  # no ]\n
    dev = _write_dev(tmp_path, [])
    stats = prof.merge_traces(str(f), dev, str(tmp_path / "m.json"))
    assert stats["host_events"] == 1 and stats["aligned"]


def test_uncompressed_device_trace(tmp_path):
    host = _write_host(tmp_path, [
        {"name": prof.TRACE_START_MARKER, "ph": "i", "ts": 0.0,
         "pid": 0, "tid": "p"},
    ])
    dev = _write_dev(tmp_path, [
        {"name": "k", "ph": "X", "ts": 1.0, "dur": 1.0, "pid": 0,
         "tid": 0},
    ], compress=False)
    stats = prof.merge_traces(host, dev, str(tmp_path / "m.json"))
    assert stats["device_events"] == 1 and stats["aligned"]


def test_marker_stamped_by_live_timeline(tmp_path):
    """start_device_trace stamps the marker through the real Timeline
    (sanity that the producer and the merge agree on the name)."""
    tl = tl_mod.start_timeline(str(tmp_path / "host.json"))
    try:
        tl.instant(prof.TRACE_START_MARKER, category="profiler",
                   args={"logdir": "x"})
    finally:
        tl_mod.stop_timeline()
    events = json.load(open(tmp_path / "host.json"))
    assert any(e["name"] == prof.TRACE_START_MARKER for e in events)
