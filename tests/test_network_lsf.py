"""NIC discovery + LSF path tests (reference: test_run.py's host/NIC
parsing and js_run cmdline-construction tests with mocked exec).
"""

import os
import subprocess
import sys
import types

import pytest

from horovod_tpu.common.exceptions import HorovodTpuError
from horovod_tpu.runner import lsf, network
from horovod_tpu.runner.lsf_bootstrap import derive_horovod_env
from horovod_tpu.runner.settings import Settings

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestNetwork:
    def test_local_interfaces_include_loopback(self):
        ifaces = network.local_interfaces()
        assert any(addr == "127.0.0.1" for addr in ifaces.values()), ifaces

    def test_resolve_by_nic_name(self):
        ifaces = network.local_interfaces()
        lo = next(n for n, a in ifaces.items() if a == "127.0.0.1")
        assert network.resolve_advertise_address(lo) == "127.0.0.1"
        # First existing interface in the list wins.
        assert network.resolve_advertise_address(
            f"doesnotexist0,{lo}") == "127.0.0.1"

    def test_resolve_unknown_nic_raises(self):
        with pytest.raises(HorovodTpuError, match="none of"):
            network.resolve_advertise_address("definitely-not-a-nic0")

    def test_common_interfaces_intersection(self):
        per_host = {
            "a": {"eth0": "10.0.0.1", "ib0": "192.168.0.1", "lo": "127.0.0.1"},
            "b": {"eth0": "10.0.0.2", "lo": "127.0.0.1"},
        }
        assert network.common_interfaces(per_host) == ["eth0"]
        assert network.common_interfaces(per_host, exclude_loopback=False) \
            == ["eth0", "lo"]

    def test_probe_remote_interfaces_mocked_ssh(self):
        def fake_run(cmd, **kw):
            assert cmd[0] == "ssh" and "hostX" in cmd
            return types.SimpleNamespace(
                returncode=0, stdout='{"eth0": "10.0.0.5"}\n', stderr="")

        out = network.probe_remote_interfaces("hostX", runner=fake_run)
        assert out == {"eth0": "10.0.0.5"}

    def test_probe_remote_failure_raises(self):
        def fake_run(cmd, **kw):
            return types.SimpleNamespace(returncode=255, stdout="",
                                         stderr="ssh: no route")

        with pytest.raises(HorovodTpuError, match="NIC probe"):
            network.probe_remote_interfaces("hostX", runner=fake_run)

    @pytest.mark.integration
    def test_launcher_honors_network_interfaces_flag(self, tmp_path):
        """--network-interfaces lo must be LIVE: workers rendezvous over
        127.0.0.1 (the lo address) and the job completes."""
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["HVD_TEST_OUT"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        lo = next(n for n, a in network.local_interfaces().items()
                  if a == "127.0.0.1")
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "--network-interfaces", lo,
             "python", os.path.join(REPO_ROOT, "tests", "data",
                                    "multiproc_main.py")],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=REPO_ROOT)
        assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
        assert (tmp_path / "rank0.json").exists()


class TestLsf:
    def test_in_lsf_job(self):
        assert not lsf.in_lsf_job({})
        assert lsf.in_lsf_job({"LSB_JOBID": "1",
                               "LSB_HOSTS": "n1 n1 n2 n2"})
        assert not lsf.in_lsf_job({"LSB_HOSTS": "n1"})  # no job id

    def test_lsf_hosts_mcpu(self):
        hosts = lsf.lsf_hosts({"LSB_MCPU_HOSTS": "batch5 1 n01 4 n02 4"})
        assert [(h.hostname, h.slots) for h in hosts] == \
            [("n01", 4), ("n02", 4)]

    def test_lsf_hosts_plain(self):
        hosts = lsf.lsf_hosts({"LSB_HOSTS": "batch1 n01 n01 n02"})
        assert [(h.hostname, h.slots) for h in hosts] == \
            [("n01", 2), ("n02", 1)]

    def test_lsf_hosts_malformed(self):
        with pytest.raises(HorovodTpuError, match="malformed"):
            lsf.lsf_hosts({"LSB_MCPU_HOSTS": "n01 4 n02"})
        with pytest.raises(HorovodTpuError, match="not inside"):
            lsf.lsf_hosts({})

    def test_build_jsrun_command(self):
        s = Settings(num_proc=8, command=["python", "train.py"])
        cmd = lsf.build_jsrun_command(s, 8)
        assert cmd[:5] == ["jsrun", "--nrs", "8", "--tasks_per_rs", "1"]
        assert cmd[-2:] == ["python", "train.py"]
        assert "horovod_tpu.runner.lsf_bootstrap" in cmd

    def test_js_run_with_mocked_jsrun(self):
        seen = {}

        def fake_run(cmd, env=None):
            seen["cmd"] = cmd
            seen["env"] = env
            return types.SimpleNamespace(returncode=0)

        s = Settings(num_proc=4, command=["python", "t.py"])
        rc = lsf.js_run(s, runner=fake_run)
        assert rc == 0
        assert seen["cmd"][0] == "jsrun"
        assert seen["env"]["HOROVOD_SIZE"] == "4"
        assert "HOROVOD_RENDEZVOUS_PORT" in seen["env"]
        assert "HOROVOD_SECRET_KEY" in seen["env"]


class TestLsfBootstrap:
    def test_derive_from_ompi(self):
        env = {
            "OMPI_COMM_WORLD_RANK": "3",
            "OMPI_COMM_WORLD_SIZE": "8",
            "OMPI_COMM_WORLD_LOCAL_RANK": "1",
            "OMPI_COMM_WORLD_LOCAL_SIZE": "4",
            "LSB_JOBID": "7",
            "LSB_MCPU_HOSTS": "n01 4 n02 4",
        }
        out = derive_horovod_env(env)
        assert out["HOROVOD_RANK"] == "3"
        assert out["HOROVOD_SIZE"] == "8"
        assert out["HOROVOD_LOCAL_RANK"] == "1"
        assert out["HOROVOD_LOCAL_SIZE"] == "4"
        assert out["HOROVOD_COORDINATOR_ADDR"] == "n01:46331"

    def test_derive_prefers_existing_coordinator(self):
        env = {
            "PMIX_RANK": "0",
            "HOROVOD_SIZE": "2",
            "HOROVOD_COORDINATOR_ADDR": "x:1",
        }
        out = derive_horovod_env(env)
        assert "HOROVOD_COORDINATOR_ADDR" not in out  # left untouched

    def test_derive_requires_rank(self):
        with pytest.raises(RuntimeError, match="no rank variable"):
            derive_horovod_env({"OMPI_COMM_WORLD_SIZE": "2"})
