"""Elastic integration tests: fake cluster on localhost.

Reference pattern (SURVEY.md §4, test/integration/elastic_common.py):
a real ElasticDriver run with a --host-discovery-script that reads a tmp
hosts file the test mutates mid-run; workers record JSON histories;
assertions cover scale-up, scale-down, failure blacklist, and min-np
abort.  "Hosts" are fake names execed locally via HVD_TPU_FAKE_LOCAL_HOSTS.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER_MAIN = os.path.join(REPO_ROOT, "tests", "data", "elastic_main.py")


class ElasticJob:
    """Drives one `horovodrun_tpu` elastic run against a mutable hosts
    file (the reference's discovery-script fakery)."""

    def __init__(self, tmp_path: Path, hosts, min_np=1, max_np=None,
                 num_epochs=6, epoch_time=0.4, extra_env=None,
                 worker=WORKER_MAIN):
        self.tmp = tmp_path
        self.hosts_file = tmp_path / "hosts.txt"
        self.set_hosts(hosts)
        self.log_dir = tmp_path / "logs"
        self.log_dir.mkdir()
        script = tmp_path / "discover.sh"
        script.write_text(f"#!/bin/sh\ncat {self.hosts_file}\n")
        script.chmod(0o755)

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env.update({
            "JAX_PLATFORMS": "cpu",
            "HVD_TPU_FAKE_LOCAL_HOSTS": "hostA,hostB,hostC",
            "TEST_LOG_DIR": str(self.log_dir),
            "NUM_EPOCHS": str(num_epochs),
            "EPOCH_TIME": str(epoch_time),
            "FAIL_MARKER": str(tmp_path / "fail_marker"),
        })
        env.update(extra_env or {})

        cmd = [sys.executable, "-m", "horovod_tpu.runner",
               "--host-discovery-script", str(script),
               "--min-np", str(min_np)]
        if max_np:
            cmd += ["--max-np", str(max_np)]
        cmd += [sys.executable, worker]
        self.proc = subprocess.Popen(
            cmd, env=env, cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    def set_hosts(self, hosts):
        # Write atomically so discovery never reads a half-written file.
        tmp = self.hosts_file.with_suffix(".tmp")
        tmp.write_text("".join(f"{h}:{s}\n" for h, s in hosts))
        tmp.rename(self.hosts_file)

    def fail_host(self, host):
        (self.tmp / "fail_marker").write_text(host)

    def wait(self, timeout=120):
        try:
            out, _ = self.proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            # SIGTERM first: the driver's handler tears down its workers
            # (a bare kill() would leak them in their own process groups).
            self.proc.terminate()
            try:
                out, _ = self.proc.communicate(timeout=15)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                out, _ = self.proc.communicate()
            raise AssertionError(f"elastic job hung; output:\n{out}")
        return self.proc.returncode, out

    def histories(self):
        hist = {}
        for f in self.log_dir.glob("worker-*.jsonl"):
            name = f.stem.replace("worker-", "")
            hist[name] = [json.loads(line) for line in f.read_text().splitlines()]
        return hist

    def wait_for_event(self, worker, event, timeout=60, min_epoch=0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            for rec in self.histories().get(worker, []):
                if rec["event"] == event and rec["epoch"] >= min_epoch:
                    return rec
            if self.proc.poll() is not None:
                break
            time.sleep(0.2)
        out = self.proc.stdout.read() if self.proc.poll() is not None else ""
        raise AssertionError(
            f"worker {worker} never reached {event} (epoch>={min_epoch}); "
            f"histories={self.histories()}; driver out:\n{out}")


@pytest.mark.integration
class TestElastic:
    def test_static_completion(self, tmp_path):
        """One host, no membership changes: clean completion."""
        job = ElasticJob(tmp_path, [("hostA", 1)], num_epochs=3,
                        epoch_time=0.1)
        rc, out = job.wait()
        assert rc == 0, out
        hist = job.histories()["hostA-0"]
        assert [r["event"] for r in hist][-2:] == ["done", "exit"]
        assert max(r["epoch"] for r in hist) == 3

    def test_scale_up(self, tmp_path):
        """Add a host mid-run: existing worker resets, new worker joins
        with the committed epoch, both finish."""
        job = ElasticJob(tmp_path, [("hostA", 1)], num_epochs=8,
                        epoch_time=0.4)
        job.wait_for_event("hostA-0", "commit", min_epoch=1)
        job.set_hosts([("hostA", 1), ("hostB", 1)])
        rc, out = job.wait()
        assert rc == 0, out
        hist = job.histories()
        a = hist["hostA-0"]
        b = hist.get("hostB-0", [])
        assert a[-1]["event"] == "exit"
        assert b and b[-1]["event"] == "exit"
        # After the bump both workers report size 2.
        assert a[-1]["size"] == 2 and b[-1]["size"] == 2
        # The joiner started from a synced (non-zero-restarted) job and
        # saw a later generation.
        assert b[0]["gen"] >= 1

    def test_scale_down_graceful(self, tmp_path):
        """Remove a host mid-run: its worker is terminated, survivor
        finishes at size 1."""
        job = ElasticJob(tmp_path, [("hostA", 1), ("hostB", 1)],
                        num_epochs=8, epoch_time=0.4)
        job.wait_for_event("hostB-0", "commit", min_epoch=1)
        job.set_hosts([("hostA", 1)])
        rc, out = job.wait()
        assert rc == 0, out
        a = job.histories()["hostA-0"]
        assert a[-1]["event"] == "exit" and a[-1]["size"] == 1

    def test_failure_blacklists_and_continues(self, tmp_path):
        """Worker dies: host blacklisted, survivor resumes from last
        commit and completes."""
        job = ElasticJob(tmp_path, [("hostA", 1), ("hostB", 1)],
                        num_epochs=8, epoch_time=0.4)
        job.wait_for_event("hostB-0", "commit", min_epoch=1)
        job.fail_host("hostB")
        rc, out = job.wait()
        assert rc == 0, out
        hist = job.histories()
        assert any(r["event"] == "failing" for r in hist["hostB-0"])
        a = hist["hostA-0"]
        assert a[-1]["event"] == "exit" and a[-1]["size"] == 1
        # Survivor kept its committed progress (epochs monotone per gen,
        # never restarted from 0 after its first commit).
        commits = [r["epoch"] for r in a if r["event"] == "commit"]
        assert commits == sorted(commits)

    def test_min_np_abort(self, tmp_path):
        """All hosts fail below --min-np: the driver aborts nonzero."""
        job = ElasticJob(tmp_path, [("hostA", 1), ("hostB", 1)],
                        min_np=2, num_epochs=50, epoch_time=0.4)
        job.wait_for_event("hostA-0", "commit", min_epoch=1)
        job.fail_host("hostA")
        rc, out = job.wait()
        assert rc != 0


@pytest.mark.integration
class TestElasticMultiprocessJax:
    """Elastic with REAL cross-process JAX collectives
    (HVD_TPU_MULTIPROCESS_JAX=1): every published rank bootstraps
    jax.distributed, state.sync() moves actual tensors between processes,
    and a reset tears the distributed runtime down and back up
    (reference: the full §3.5 recovery cycle)."""

    WORKER = os.path.join(REPO_ROOT, "tests", "data",
                          "elastic_tensor_main.py")

    def test_scale_up_syncs_tensor_state(self, tmp_path):
        job = ElasticJob(
            tmp_path, [("hostA", 1)], num_epochs=8, epoch_time=0.4,
            extra_env={"HVD_TPU_MULTIPROCESS_JAX": "1",
                       # one CPU device per process: the pytest session's
                       # 8-virtual-device XLA_FLAGS must not leak in
                       "XLA_FLAGS": ""},
            worker=self.WORKER)
        job.wait_for_event("hostA-0", "commit", min_epoch=2)
        job.set_hosts([("hostA", 1), ("hostB", 1)])
        rc, out = job.wait(timeout=240)
        assert rc == 0, out
        hist = job.histories()
        a, b = hist["hostA-0"], hist.get("hostB-0", [])
        assert b, f"joiner never started: {out}"
        assert a[-1]["event"] == "exit" and b[-1]["event"] == "exit"
        # Both finished at size 2 under a real 2-process world.
        assert a[-1]["size"] == 2 and b[-1]["size"] == 2
        # The joiner's FIRST commit carries synced (non-zero) params —
        # rank-0's committed trajectory reached it via a real
        # cross-process broadcast, not a fresh start.
        first_b_commit = next(r for r in b if r["event"] == "commit")
        assert first_b_commit["epoch"] >= 3
        assert all(p > 2.0 for p in first_b_commit["params"])
        # And the final params agree exactly across workers.
        assert a[-1]["params"] == b[-1]["params"]
