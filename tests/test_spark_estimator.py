"""Spark Estimator tests (reference: test/single/test_spark.py estimator
sections + test_spark_keras.py / test_spark_torch.py — estimator fit on
tiny DataFrames against a local cluster; store backends against temp
dirs).

Here the "cluster" is the LocalBackend (real worker processes through
runner/api.run on the CPU platform) and DataFrames are pandas — the
exact degrade path the estimator layer documents.
"""

import os

import numpy as np
import pandas as pd
import pytest

from horovod_tpu.common.exceptions import HorovodTpuError
from horovod_tpu.spark.common import (
    EstimatorParams, LocalBackend, LocalStore, Store,
)
from horovod_tpu.spark.common.util import load_shard, prepare_data


def make_df(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n).astype(np.float32)
    x2 = rng.normal(size=n).astype(np.float32)
    y = 2.0 * x1 - 1.0 * x2 + 0.5
    return pd.DataFrame({"x1": x1, "x2": x2, "y": y.astype(np.float32)})


# ---------------------------------------------------------------------------
# Store
# ---------------------------------------------------------------------------

class TestStore:
    def test_create_local(self, tmp_path):
        s = Store.create(str(tmp_path / "store"))
        assert isinstance(s, LocalStore)
        assert s.prefix_path == str(tmp_path / "store")

    def test_create_file_scheme(self, tmp_path):
        s = Store.create(f"file://{tmp_path}/fs")
        assert s.prefix_path == f"{tmp_path}/fs"

    @pytest.mark.parametrize("url", ["s3://b/x", "abfss://c@a/x"])
    def test_object_scheme_without_client_raises(self, url):
        with pytest.raises(HorovodTpuError, match="filesystem client"):
            Store.create(url)

    def test_hdfs_without_client_raises(self):
        with pytest.raises(HorovodTpuError, match="hadoop client"):
            Store.create("hdfs://nn/x")

    def test_paths_and_atomic_write(self, tmp_path):
        s = Store.create(str(tmp_path))
        assert "intermediate_train_data" in s.get_train_data_path("r1")
        assert s.get_checkpoint_path("r1").startswith(s.get_run_path("r1"))
        p = os.path.join(s.get_run_path("r1"), "blob.bin")
        s.write_bytes(p, b"abc")
        assert s.read_bytes(p) == b"abc"
        assert not [f for f in os.listdir(os.path.dirname(p))
                    if ".tmp." in f]

    def test_owned_tempdir_cleanup(self):
        s = Store.create(None)
        prefix = s.prefix_path
        assert os.path.isdir(prefix)
        s.cleanup()
        assert not os.path.exists(prefix)


class _MockFs:
    """In-memory duck-typed filesystem client (the injection seam real
    cluster deployments fill with pyarrow/fsspec)."""

    def __init__(self):
        self.files = {}
        self.dirs = set()
        self.renames = []

    class _Buf:
        def __init__(self, fs, path, mode):
            import io

            self._fs, self._path, self._mode = fs, path, mode
            self._io = io.BytesIO(fs.files.get(path, b"")
                                  if "r" in mode else b"")

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            if "w" in self._mode:
                self._fs.files[self._path] = self._io.getvalue()

        def read(self):
            return self._io.getvalue()

        def write(self, data):
            self._io.write(data)

    def open(self, path, mode="rb"):
        return self._Buf(self, path, mode)

    def exists(self, path):
        return path in self.files or path in self.dirs or any(
            f.startswith(path + "/") for f in self.files)

    def mkdirs(self, path):
        self.dirs.add(path)

    def ls(self, path):
        out = set()
        for f in list(self.files) + list(self.dirs):
            if f.startswith(path + "/"):
                out.add(f[len(path) + 1:].split("/")[0])
        return sorted(path + "/" + o for o in out)

    def rename(self, src, dst):
        # HDFS semantics: rename refuses to overwrite an existing dst.
        if dst in self.files:
            raise FileExistsError(dst)
        self.renames.append((src, dst))
        self.files[dst] = self.files.pop(src)

    def delete(self, path):
        self.files.pop(path, None)


class TestRemoteStores:
    """URI-level store routing with mocked clients (reference:
    store.py HDFSStore ≈L200-400 / DBFSLocalStore; r03 verdict
    missing-item 4)."""

    def test_create_routes_hdfs_with_injected_client(self):
        from horovod_tpu.spark.common.store import HDFSStore

        fs = _MockFs()
        s = Store.create("hdfs://nn:8020/warehouse", filesystem=fs)
        assert isinstance(s, HDFSStore)
        assert s.get_train_data_path("r1") == \
            "hdfs://nn:8020/warehouse/intermediate_train_data/r1"

    def test_create_routes_object_store_with_injected_client(self):
        from horovod_tpu.spark.common.store import FilesystemStore

        s = Store.create("s3://bucket/prefix", filesystem=_MockFs())
        assert isinstance(s, FilesystemStore)

    def test_remote_io_roundtrip_atomic(self):
        fs = _MockFs()
        s = Store.create("hdfs://nn/wh", filesystem=fs)
        p = s.get_run_path("r2") + "/blob.bin"
        s.write_bytes(p, b"payload")
        assert s.exists(p) and s.read_bytes(p) == b"payload"
        # Atomic: written to a tmp name then renamed.
        assert fs.renames and fs.renames[0][1] == p
        assert s.list_dir(s.get_run_path("r2")) == ["blob.bin"]
        assert s.saving_runs() == ["r2"]

    def test_remote_rewrite_same_path_survives_hdfs_rename(self):
        # HDFS rename does not overwrite: the second checkpoint write to
        # the same path must still land (store moves dst aside to a .bak
        # and cleans it up after the swap).
        fs = _MockFs()
        s = Store.create("hdfs://nn/wh", filesystem=fs)
        p = s.get_checkpoint_path("r3")
        s.write_bytes(p, b"epoch1")
        s.write_bytes(p, b"epoch2")
        assert s.read_bytes(p) == b"epoch2"
        assert not [f for f in fs.files if ".tmp." in f or ".bak" in f]

    def test_rewrite_never_deletes_checkpoint_outright(self):
        # Crash-safety: at no point may the destination be deleted while
        # no replacement exists — the old file is renamed aside, so a
        # crash mid-swap leaves a recoverable .bak (r04 review finding).
        fs = _MockFs()
        deleted = []
        orig_delete = fs.delete
        fs.delete = lambda path: (deleted.append(path), orig_delete(path))
        s = Store.create("hdfs://nn/wh", filesystem=fs)
        p = s.get_checkpoint_path("r4")
        s.write_bytes(p, b"epoch1")
        s.write_bytes(p, b"epoch2")
        assert p not in deleted
        assert all(".bak" in d for d in deleted)

    def test_strip_scheme_drops_authority(self):
        from horovod_tpu.spark.common.store import _strip_scheme

        # hdfs://host:port/a/b must resolve to the ABSOLUTE /a/b — the
        # client is already bound to the authority (r04 review finding).
        assert _strip_scheme("hdfs://nn:8020/tmp/run/x") == "/tmp/run/x"
        assert _strip_scheme("hdfs:///tmp/run/x") == "/tmp/run/x"
        assert _strip_scheme("hdfs://nn:8020") == "/"
        assert _strip_scheme("/plain/path") == "/plain/path"

    def test_checkpoint_path_layout_matches_local(self, tmp_path):
        remote = Store.create("hdfs://nn/wh", filesystem=_MockFs())
        local = Store.create(str(tmp_path))
        rel = lambda s, p: p.replace(s.prefix_path, "")  # noqa: E731
        assert rel(remote, remote.get_checkpoint_path("x")).replace(
            "\\", "/") == rel(local, local.get_checkpoint_path("x")).replace(
            os.sep, "/")

    def test_dbfs_maps_to_fuse_mount(self):
        from horovod_tpu.spark.common.store import DBFSLocalStore

        s = Store.create("dbfs:/ml/store")
        assert isinstance(s, DBFSLocalStore)
        assert s.prefix_path == "/dbfs/ml/store"
        assert DBFSLocalStore.normalize_datasets_dir("dbfs:/a/b") == \
            "/dbfs/a/b"


# ---------------------------------------------------------------------------
# Params machinery
# ---------------------------------------------------------------------------

class TestParams:
    def test_constructor_and_fluent_accessors(self):
        p = EstimatorParams(batch_size=16)
        assert p.batch_size == 16
        assert p.setEpochs(7) is p
        assert p.getEpochs() == 7
        assert p.epochs == 7

    def test_camel_case_accessors_map_to_snake_params(self):
        p = (EstimatorParams().setFeatureCols(["x1"]).setLabelCols(["y"])
             .setBatchSize(8).setRandomSeed(3))
        assert p.feature_cols == ["x1"]
        assert p.getLabelCols() == ["y"]
        assert p.batch_size == 8 and p.random_seed == 3

    def test_unknown_param_raises(self):
        with pytest.raises(TypeError, match="unknown params"):
            EstimatorParams(nonsense=1)

    def test_unknown_attribute_raises(self):
        with pytest.raises(AttributeError):
            EstimatorParams().setNonsense  # noqa: B018


# ---------------------------------------------------------------------------
# Data materialization
# ---------------------------------------------------------------------------

class TestPrepareData:
    def test_shards_equal_size(self, tmp_path):
        # 20 rows / 3 shards: equal 6-row shards, remainder 2 dropped —
        # unequal shards would desynchronize per-batch collectives.
        df = make_df(20)
        s = Store.create(str(tmp_path))
        meta = prepare_data(df, s, "r", 3, ["x1", "x2"], ["y"],
                            shuffle=False)
        assert meta == {"train_rows": 18, "val_rows": 0,
                        "features_dim": 2, "labels_dim": 1}
        xs = []
        for r in range(3):
            x, y = load_shard(s.get_train_data_path("r"), r)
            assert x.shape == (6, 2) and y.shape == (6, 1)
            xs.append(x)
        got = np.concatenate(xs)[:, 0]
        # shards partition (a subset of) the input, no duplicates
        assert len(np.unique(got)) == 18
        assert set(got).issubset(set(df["x1"].to_numpy()))

    def test_even_split_covers_all_rows(self, tmp_path):
        s = Store.create(str(tmp_path))
        meta = prepare_data(make_df(24), s, "r", 3, ["x1"], ["y"])
        assert meta["train_rows"] == 24

    def test_validation_fraction_single_shared_shard(self, tmp_path):
        from horovod_tpu.spark.common.util import load_val

        s = Store.create(str(tmp_path))
        meta = prepare_data(make_df(40), s, "r", 2, ["x1"], ["y"],
                            validation=0.25, seed=1)
        assert meta["val_rows"] == 10
        # ONE shared shard (.x/.y npy pair), not a copy per rank
        assert s.list_dir(s.get_val_data_path("r")) == [
            "val.x.npy", "val.y.npy"]
        xv, yv = load_val(s.get_val_data_path("r"))
        assert len(xv) == 10 and len(yv) == 10

    def test_validation_column(self, tmp_path):
        df = make_df(10)
        df["is_val"] = [True] * 3 + [False] * 7
        s = Store.create(str(tmp_path))
        meta = prepare_data(df, s, "r", 2, ["x1"], ["y"],
                            validation="is_val")
        # 7 train rows → equal shards of 3, remainder dropped
        assert meta == dict(meta, train_rows=6, val_rows=3)

    def test_too_few_rows_raises(self, tmp_path):
        with pytest.raises(HorovodTpuError, match="needs at least one row"):
            prepare_data(make_df(2), Store.create(str(tmp_path)), "r", 4,
                         ["x1"], ["y"])

    def test_missing_column_raises(self, tmp_path):
        with pytest.raises(HorovodTpuError, match="not in DataFrame"):
            prepare_data(make_df(8), Store.create(str(tmp_path)), "r", 2,
                         ["nope"], ["y"])

    def test_array_valued_cells_flatten(self, tmp_path):
        df = pd.DataFrame({
            "img": [np.ones((2, 2), np.float32) * i for i in range(6)],
            "y": np.arange(6, dtype=np.float32),
        })
        s = Store.create(str(tmp_path))
        meta = prepare_data(df, s, "r", 2, ["img"], ["y"], shuffle=False)
        assert meta["features_dim"] == 4

    def test_integer_labels_preserved(self, tmp_path):
        df = pd.DataFrame({"x1": np.arange(8, dtype=np.float32),
                           "cls": np.arange(8) % 3})
        s = Store.create(str(tmp_path))
        prepare_data(df, s, "r", 2, ["x1"], ["cls"], shuffle=False)
        _, y = load_shard(s.get_train_data_path("r"), 0)
        assert y.dtype == np.int64

    def test_validation_column_typo_raises(self, tmp_path):
        with pytest.raises(HorovodTpuError, match="validation column"):
            prepare_data(make_df(8), Store.create(str(tmp_path)), "r", 2,
                         ["x1"], ["y"], validation="is_vall")

    def test_output_frame_shape_mismatch_raises(self):
        from horovod_tpu.spark.common.util import to_output_frame

        pdf = make_df(4)
        with pytest.raises(HorovodTpuError, match="outputs per row"):
            to_output_frame(pdf, ["mu", "sigma"], np.zeros((4, 3)))

    def test_output_frame_single_col_array_preds(self):
        from horovod_tpu.spark.common.util import to_output_frame

        out = to_output_frame(make_df(4), ["p"], np.zeros((4, 3)))
        assert len(out["p"][0]) == 3


class TestShardDataLoader:
    def _write(self, tmp_path, n=32):
        from horovod_tpu.spark.common.util import prepare_data

        s = Store.create(str(tmp_path))
        df = make_df(n)
        prepare_data(df, s, "r", 2, ["x1", "x2"], ["y"], shuffle=False)
        return s.get_train_data_path("r"), df

    def test_mmap_batches_cover_shard(self, tmp_path):
        from horovod_tpu.spark.common import ShardDataLoader

        train_dir, _ = self._write(tmp_path)
        loader = ShardDataLoader(train_dir, 0, batch_size=4, shuffle=True,
                                 seed=0)
        assert loader.rows == 16 and len(loader) == 4
        seen = []
        for xb, yb in loader.epoch(0):
            assert xb.shape == (4, 2) and yb.shape == (4, 1)
            seen.append(xb)
        assert len(np.unique(np.concatenate(seen)[:, 0])) == 16

    def test_epoch_shuffles_differ_but_are_seeded(self, tmp_path):
        from horovod_tpu.spark.common import ShardDataLoader

        train_dir, _ = self._write(tmp_path)
        # Batch indexes are sorted (mmap locality), so compare batch
        # COMPOSITION — the thing shuffling actually varies for SGD.
        loader = ShardDataLoader(train_dir, 0, batch_size=8, seed=3)
        e0 = set(next(iter(loader.epoch(0)))[0][:, 0].tolist())
        e1 = set(next(iter(loader.epoch(1)))[0][:, 0].tolist())
        e0b = set(next(iter(loader.epoch(0)))[0][:, 0].tolist())
        assert e0 != e1          # different epochs pick different rows
        assert e0 == e0b         # same epoch reproducible

    def test_drop_last_keeps_batches_equal(self, tmp_path):
        from horovod_tpu.spark.common import ShardDataLoader

        train_dir, _ = self._write(tmp_path, n=30)  # 15 rows per shard
        loader = ShardDataLoader(train_dir, 1, batch_size=4)
        batches = list(loader.epoch(0))
        assert len(batches) == 3                 # 15 // 4, last dropped
        full = ShardDataLoader(train_dir, 1, batch_size=4,
                               drop_last=False)
        assert len(list(full.epoch(0))) == 4

    def test_missing_shard_raises(self, tmp_path):
        from horovod_tpu.spark.common import ShardDataLoader

        train_dir, _ = self._write(tmp_path)
        with pytest.raises(HorovodTpuError, match="no shard"):
            ShardDataLoader(train_dir, 7, batch_size=4)


class TestOptimizerRecipe:
    def test_param_groups_preserved(self):
        import torch

        from horovod_tpu.spark.torch import (
            _build_optimizer, _optimizer_recipe,
        )

        net = torch.nn.Sequential(torch.nn.Linear(2, 4),
                                  torch.nn.Linear(4, 1))
        opt = torch.optim.SGD([
            {"params": net[0].parameters(), "lr": 0.01},
            {"params": net[1].parameters(), "lr": 0.001, "momentum": 0.5},
        ], lr=0.1)
        recipe = _optimizer_recipe(opt)
        # Simulate the worker: same architecture, fresh params.
        net2 = torch.nn.Sequential(torch.nn.Linear(2, 4),
                                   torch.nn.Linear(4, 1))
        rebuilt = _build_optimizer(recipe, net2)
        assert len(rebuilt.param_groups) == 2
        assert rebuilt.param_groups[0]["lr"] == 0.01
        assert rebuilt.param_groups[1]["lr"] == 0.001
        assert rebuilt.param_groups[1]["momentum"] == 0.5
        assert rebuilt.param_groups[0]["params"] == list(
            net2[0].parameters())

    def test_out_of_order_groups_raise(self):
        import torch

        from horovod_tpu.spark.torch import (
            _build_optimizer, _optimizer_recipe,
        )
        from horovod_tpu.common.exceptions import HorovodTpuError

        net = torch.nn.Sequential(torch.nn.Linear(2, 4),
                                  torch.nn.Linear(4, 1))
        # Groups in REVERSE of model.parameters() order: silent
        # positional rebind would swap the lrs — must raise instead.
        opt = torch.optim.SGD([
            {"params": net[1].parameters(), "lr": 0.001},
            {"params": net[0].parameters(), "lr": 0.01},
        ], lr=0.1)
        with pytest.raises(HorovodTpuError, match="order"):
            _build_optimizer(_optimizer_recipe(opt), net)

    def test_param_count_mismatch_raises(self):
        import torch

        from horovod_tpu.spark.torch import (
            _build_optimizer, _optimizer_recipe,
        )

        net = torch.nn.Linear(2, 1)
        recipe = _optimizer_recipe(torch.optim.SGD([net.weight], lr=0.1))
        with pytest.raises(HorovodTpuError, match="covered 1 params"):
            _build_optimizer(recipe, net)  # model has weight+bias = 2


# ---------------------------------------------------------------------------
# Estimator validation (fast, no workers)
# ---------------------------------------------------------------------------

class TestEstimatorValidation:
    def test_missing_model_raises(self):
        from horovod_tpu.spark.torch import TorchEstimator

        with pytest.raises(HorovodTpuError, match="model is required"):
            TorchEstimator(feature_cols=["x1"], label_cols=["y"]).fit(
                make_df(8))

    def test_missing_cols_raises(self):
        from horovod_tpu.spark.torch import TorchEstimator

        with pytest.raises(HorovodTpuError, match="feature_cols"):
            TorchEstimator(model=object()).fit(make_df(8))

    def test_torch_callbacks_raise(self):
        import torch

        from horovod_tpu.spark.torch import TorchEstimator

        net = torch.nn.Linear(2, 1)
        est = TorchEstimator(model=net,
                             optimizer=torch.optim.SGD(net.parameters(),
                                                       lr=0.1),
                             loss=torch.nn.functional.mse_loss,
                             callbacks=[object()],
                             feature_cols=["x1"], label_cols=["y"],
                             backend=LocalBackend(1))
        with pytest.raises(HorovodTpuError, match="does not take callbacks"):
            est.fit(make_df(8))

    def test_cluster_spark_backend_rejects_tempdir_store(self, monkeypatch):
        import sys
        import types

        from horovod_tpu.spark.common.backend import SparkBackend
        from horovod_tpu.spark.common.estimator import HorovodEstimator

        mod = types.ModuleType("pyspark")
        mod.SparkContext = types.SimpleNamespace(
            _active_spark_context=types.SimpleNamespace(
                master="spark://cluster:7077"))
        monkeypatch.setitem(sys.modules, "pyspark", mod)
        with pytest.raises(HorovodTpuError, match="shared/NFS"):
            HorovodEstimator._check_store_reachable(
                Store.create(None), SparkBackend(2))
        # explicit user path: accepted (their responsibility)
        HorovodEstimator._check_store_reachable(
            Store.create("/tmp/shared_mount_x"), SparkBackend(2))

    def test_bad_compression_raises(self):
        import torch

        from horovod_tpu.spark.torch import TorchEstimator

        net = torch.nn.Linear(2, 1)
        est = TorchEstimator(model=net,
                             optimizer=torch.optim.SGD(net.parameters(),
                                                       lr=0.1),
                             loss=torch.nn.functional.mse_loss,
                             compression="int4",
                             feature_cols=["x1"], label_cols=["y"],
                             backend=LocalBackend(1))
        with pytest.raises(HorovodTpuError, match="compression must be"):
            est.fit(make_df(8))

    def test_validation_precedes_data_prep(self, tmp_path):
        # A bad-param fit must fail BEFORE prepare_data, leaving no
        # dataset-sized shard scratch in the store.
        import torch

        from horovod_tpu.spark.torch import TorchEstimator

        store = Store.create(str(tmp_path))
        net = torch.nn.Linear(2, 1)
        est = TorchEstimator(model=net, optimizer="sgd",
                             loss=torch.nn.functional.mse_loss,
                             feature_cols=["x1", "x2"], label_cols=["y"],
                             store=store, run_id="leakcheck",
                             backend=LocalBackend(1))
        with pytest.raises(HorovodTpuError, match="optimizer must be"):
            est.fit(make_df(16))
        assert not os.path.exists(store.get_train_data_path("leakcheck"))

    def test_bad_torch_optimizer_raises(self):
        import torch

        from horovod_tpu.spark.torch import TorchEstimator

        net = torch.nn.Linear(2, 1)
        est = TorchEstimator(model=net, optimizer="sgd",
                             loss=torch.nn.functional.mse_loss,
                             feature_cols=["x1", "x2"], label_cols=["y"],
                             backend=LocalBackend(1))
        with pytest.raises(HorovodTpuError, match="optimizer must be"):
            est.fit(make_df(8))


# ---------------------------------------------------------------------------
# End-to-end fits on real local worker processes
# ---------------------------------------------------------------------------

@pytest.mark.integration
class TestTorchEstimatorFit:
    @pytest.mark.slow
    def test_fit_transform_2proc(self, tmp_path):
        import torch

        from horovod_tpu.spark.torch import TorchEstimator

        torch.manual_seed(0)
        net = torch.nn.Linear(2, 1)
        df = make_df(64)
        est = TorchEstimator(
            model=net,
            optimizer=torch.optim.SGD(net.parameters(), lr=0.1),
            loss=torch.nn.functional.mse_loss,
            feature_cols=["x1", "x2"], label_cols=["y"],
            batch_size=16, epochs=8, validation=0.2, random_seed=0,
            store=Store.create(str(tmp_path)), run_id="torchrun",
            backend=LocalBackend(2), verbose=0)
        model = est.fit(df)

        hist = model.get_history()
        assert len(hist["loss"]) == 8
        assert hist["loss"][-1] < hist["loss"][0]
        assert len(hist["val_loss"]) == 8

        out = model.transform(df)
        assert "prediction" in out.columns
        preds = np.asarray([float(np.ravel(v)[0]) for v in out["prediction"]])
        # Linear data, linear model: fit should be decent after 8 epochs.
        err = np.mean((preds - df["y"].to_numpy()) ** 2)
        assert err < 0.5, f"mse {err}"

        # Rank-0 checkpoint landed in the store's run path.
        ckpt = est.store.get_checkpoint_path("torchrun")
        assert os.path.exists(ckpt)

        # getModel returns a torch module usable directly.
        m = model.getModel()
        assert isinstance(m, torch.nn.Module)


@pytest.mark.integration
class TestKerasEstimatorFit:
    @pytest.mark.slow
    def test_fit_transform_2proc(self, tmp_path):
        import tensorflow as tf

        from horovod_tpu.spark.keras import KerasEstimator

        tf.keras.utils.set_random_seed(0)
        model = tf.keras.Sequential([
            tf.keras.layers.Input((2,)),
            tf.keras.layers.Dense(1),
        ])
        df = make_df(64)
        est = KerasEstimator(
            model=model,
            optimizer=tf.keras.optimizers.SGD(0.1),
            loss="mse",
            feature_cols=["x1", "x2"], label_cols=["y"],
            batch_size=16, epochs=6, random_seed=0,
            store=Store.create(str(tmp_path)), run_id="kerasrun",
            backend=LocalBackend(2), verbose=0)
        fitted = est.fit(df)

        hist = fitted.get_history()
        assert len(hist["loss"]) == 6
        assert hist["loss"][-1] < hist["loss"][0]

        out = fitted.transform(df)
        assert "prediction" in out.columns
        preds = np.asarray([float(np.ravel(v)[0]) for v in out["prediction"]])
        err = np.mean((preds - df["y"].to_numpy()) ** 2)
        assert err < 0.5, f"mse {err}"

        assert os.path.exists(est.store.get_checkpoint_path("kerasrun"))


# ---------------------------------------------------------------------------
# Lightning estimator (duck-typed LightningModule contract)
# ---------------------------------------------------------------------------

def _lit_import():
    import sys

    data_dir = os.path.join(os.path.dirname(__file__), "data")
    if data_dir not in sys.path:
        sys.path.insert(0, data_dir)
    import lit_module

    return data_dir, lit_module


class TestLightningValidation:
    def test_contract_violation_raises(self):
        import torch

        from horovod_tpu.spark.lightning import LightningEstimator

        est = LightningEstimator(model=torch.nn.Linear(2, 1),
                                 feature_cols=["x1"], label_cols=["y"],
                                 backend=LocalBackend(1))
        with pytest.raises(HorovodTpuError, match="LightningModule"):
            est.fit(make_df(8))

    def test_loss_param_rejected(self):
        from horovod_tpu.spark.lightning import LightningEstimator

        _, lit = _lit_import()
        est = LightningEstimator(model=lit.LitRegression(),
                                 loss="mse",
                                 feature_cols=["x1"], label_cols=["y"],
                                 backend=LocalBackend(1))
        with pytest.raises(HorovodTpuError, match="come from the"):
            est.fit(make_df(8))

    def test_single_optimizer_forms(self):
        import torch

        from horovod_tpu.spark.lightning import _single_optimizer

        _, lit = _lit_import()
        m = lit.LitRegression()
        opt = torch.optim.SGD(m.parameters(), lr=0.1)
        sched = torch.optim.lr_scheduler.StepLR(opt, step_size=1)

        assert _single_optimizer(opt) == (opt, [])
        assert _single_optimizer([opt]) == (opt, [])
        assert _single_optimizer(([opt], [sched])) == (opt, [sched])
        # Lightning also allows the two-list form AS a list.
        assert _single_optimizer([[opt], [sched]]) == (opt, [sched])
        assert _single_optimizer(
            {"optimizer": opt, "lr_scheduler": {"scheduler": sched,
                                                "interval": "epoch"}}
        ) == (opt, [sched])
        with pytest.raises(HorovodTpuError, match="single-optimizer"):
            _single_optimizer(([opt, opt], []))
        # The bare GAN form `return opt_g, opt_d` is a 2-tuple of
        # optimizers, not ([opts], [scheds]) — explicit rejection, not
        # a TypeError.
        opt2 = torch.optim.SGD(m.parameters(), lr=0.1)
        with pytest.raises(HorovodTpuError, match="single-optimizer"):
            _single_optimizer((opt, opt2))
        # Non-epoch scheduler cadence is refused, never approximated.
        with pytest.raises(HorovodTpuError, match="once per epoch"):
            _single_optimizer({"optimizer": opt,
                               "lr_scheduler": {"scheduler": sched,
                                                "interval": "step"}})
        # Malformed dicts get explicit rejections, not KeyErrors.
        with pytest.raises(HorovodTpuError, match="'optimizer' key"):
            _single_optimizer({"lr_scheduler": {"scheduler": sched}})
        with pytest.raises(HorovodTpuError, match="'scheduler' key"):
            _single_optimizer({"optimizer": opt,
                               "lr_scheduler": {"interval": "epoch"}})

    def test_multi_opt_module_fails_on_driver(self, tmp_path):
        # Unsupported configs are rejected driver-side, BEFORE data prep.
        from horovod_tpu.spark.lightning import LightningEstimator

        _, lit = _lit_import()
        store = Store.create(str(tmp_path))
        est = LightningEstimator(model=lit.LitMultiOpt(),
                                 feature_cols=["x1"], label_cols=["y"],
                                 store=store, run_id="multiopt",
                                 backend=LocalBackend(1))
        with pytest.raises(HorovodTpuError, match="single-optimizer"):
            est.fit(make_df(8))
        assert not os.path.exists(store.get_train_data_path("multiopt"))

    def test_validation_without_validation_step_rejected(self):
        import torch

        from horovod_tpu.spark.lightning import LightningEstimator

        _, lit = _lit_import()

        class NoVal(torch.nn.Module):
            training_step = lit.LitRegression.training_step
            configure_optimizers = lit.LitRegression.configure_optimizers

            def __init__(self):
                super().__init__()
                self.net = torch.nn.Linear(1, 1)
                self.lr = 0.1

            def forward(self, x):
                return self.net(x)

        est = LightningEstimator(model=NoVal(), validation=0.2,
                                 feature_cols=["x1"], label_cols=["y"],
                                 backend=LocalBackend(1))
        with pytest.raises(HorovodTpuError, match="no validation_step"):
            est.fit(make_df(8))

    def test_callbacks_rejected(self):
        from horovod_tpu.spark.lightning import LightningEstimator

        _, lit = _lit_import()
        est = LightningEstimator(model=lit.LitRegression(),
                                 callbacks=[object()],
                                 feature_cols=["x1"], label_cols=["y"],
                                 backend=LocalBackend(1))
        with pytest.raises(HorovodTpuError, match="does not take callbacks"):
            est.fit(make_df(8))

    def test_multi_optimizer_module_raises(self):
        from horovod_tpu.spark.lightning import _single_optimizer

        _, lit = _lit_import()
        with pytest.raises(HorovodTpuError, match="single-optimizer"):
            _single_optimizer(lit.LitMultiOpt().configure_optimizers())

    def test_step_loss_forms(self):
        import torch

        from horovod_tpu.spark.lightning import _step_loss

        t = torch.tensor(1.0)
        assert _step_loss(t) is t
        assert _step_loss({"loss": t, "log": {}}) is t
        with pytest.raises(HorovodTpuError, match="loss"):
            _step_loss({"log": {}})


@pytest.mark.integration
class TestLightningEstimatorFit:
    @pytest.mark.slow
    def test_fit_transform_2proc(self, tmp_path, monkeypatch):
        import torch

        from horovod_tpu.spark.lightning import LightningEstimator

        data_dir, lit = _lit_import()
        # The fitted module pickles by class reference; workers must be
        # able to import lit_module (they inherit the environment).
        monkeypatch.setenv(
            "PYTHONPATH",
            data_dir + os.pathsep + os.environ.get("PYTHONPATH", ""))
        torch.manual_seed(0)
        df = make_df(64)
        est = LightningEstimator(
            model=lit.LitRegression(lr=0.1),
            feature_cols=["x1", "x2"], label_cols=["y"],
            batch_size=16, epochs=8, validation=0.2, random_seed=0,
            store=Store.create(str(tmp_path)), run_id="litrun",
            backend=LocalBackend(2), verbose=0)
        model = est.fit(df)

        hist = model.get_history()
        assert len(hist["loss"]) == 8
        assert hist["loss"][-1] < hist["loss"][0]
        assert len(hist["val_loss"]) == 8

        out = model.transform(df)
        assert "prediction" in out.columns
        preds = np.asarray([float(np.ravel(v)[0])
                            for v in out["prediction"]])
        err = np.mean((preds - df["y"].to_numpy()) ** 2)
        assert err < 0.5, f"mse {err}"

        assert os.path.exists(est.store.get_checkpoint_path("litrun"))

        # The returned module is the trained rank-0 instance: the epoch
        # hooks ran once per epoch.
        m = model.getModel()
        assert m.epoch_starts == 8 and m.epoch_ends == 8

    def test_scheduler_config_1proc(self, tmp_path, monkeypatch):
        from horovod_tpu.spark.lightning import LightningEstimator

        data_dir, lit = _lit_import()
        monkeypatch.setenv(
            "PYTHONPATH",
            data_dir + os.pathsep + os.environ.get("PYTHONPATH", ""))
        est = LightningEstimator(
            model=lit.LitTupleConfig(lr=0.1),
            feature_cols=["x1", "x2"], label_cols=["y"],
            batch_size=16, epochs=3, random_seed=0,
            store=Store.create(str(tmp_path)), run_id="litsched",
            backend=LocalBackend(1), verbose=0)
        model = est.fit(make_df(48))
        hist = model.get_history()
        assert len(hist["loss"]) == 3
        assert hist["loss"][-1] < hist["loss"][0]
