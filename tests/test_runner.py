"""Runner/launcher tests.

Mirrors the reference's test/single/test_run.py strategy (SURVEY.md §4):
parse_args flag surface, host parsing, get_host_assignments rank math,
rendezvous KV semantics (reference test_http_server.py), and real
multi-process launches over localhost.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from horovod_tpu.common.exceptions import HorovodTpuError
from horovod_tpu.runner import (
    HostInfo,
    parse_hosts,
    parse_hostfile,
    get_host_assignments,
)
from horovod_tpu.runner.launch import check_build, make_settings, parse_args
from horovod_tpu.runner.rendezvous import (
    RendezvousClient,
    RendezvousServer,
    new_secret,
)


# ---------------------------------------------------------------------------
# Host parsing (reference: test_run.py host parsing cases)
# ---------------------------------------------------------------------------

class TestHosts:
    def test_parse_hosts(self):
        hosts = parse_hosts("a:2,b:4")
        assert hosts == [HostInfo("a", 2), HostInfo("b", 4)]

    def test_parse_hosts_invalid(self):
        for bad in ("a", "a:", ":2", "a:2:3", "a:x", ""):
            with pytest.raises(HorovodTpuError):
                parse_hosts(bad)

    def test_parse_hosts_duplicate(self):
        with pytest.raises(HorovodTpuError):
            parse_hosts("a:2,a:2")

    def test_parse_hostfile(self, tmp_path):
        hf = tmp_path / "hosts"
        hf.write_text(
            "# comment\n"
            "node1 slots=2\n"
            "node2 4\n"
            "node3\n"
            "\n"
        )
        hosts = parse_hostfile(str(hf))
        assert hosts == [HostInfo("node1", 2), HostInfo("node2", 4),
                         HostInfo("node3", 1)]

    def test_assignments_basic(self):
        slots = get_host_assignments(parse_hosts("a:2,b:2"), 4)
        assert [(s.hostname, s.rank, s.local_rank, s.cross_rank)
                for s in slots] == [
            ("a", 0, 0, 0), ("a", 1, 1, 0), ("b", 2, 0, 1), ("b", 3, 1, 1)]
        for s in slots:
            assert s.size == 4 and s.local_size == 2 and s.cross_size == 2

    def test_assignments_uneven(self):
        # Reference rank math: cross_size per local_rank column.
        slots = get_host_assignments(parse_hosts("a:1,b:2"), 3)
        a0, b0, b1 = slots
        assert (a0.hostname, a0.rank, a0.local_rank) == ("a", 0, 0)
        assert (b0.hostname, b0.rank, b0.local_rank) == ("b", 1, 0)
        assert (b1.hostname, b1.rank, b1.local_rank) == ("b", 2, 1)
        assert a0.cross_size == 2 and b0.cross_size == 2
        assert b1.cross_size == 1 and b1.cross_rank == 0
        assert a0.local_size == 1 and b0.local_size == 2

    def test_assignments_insufficient(self):
        with pytest.raises(HorovodTpuError):
            get_host_assignments(parse_hosts("a:1"), 2)

    def test_assignments_max_np(self):
        slots = get_host_assignments(parse_hosts("a:4"), 1, max_np=2)
        assert len(slots) == 2


# ---------------------------------------------------------------------------
# CLI arg surface (reference: test_run.py parse_args cases)
# ---------------------------------------------------------------------------

class TestParseArgs:
    def test_minimal(self):
        args = parse_args(["-np", "2", "python", "train.py"])
        assert args.np == 2
        assert args.command == ["python", "train.py"]

    def test_full_surface(self):
        args = parse_args([
            "-np", "8", "-H", "a:4,b:4", "--timeline-filename", "/tmp/t.json",
            "--fusion-threshold-mb", "32", "--cycle-time-ms", "3.5",
            "--cache-capacity", "2048", "--autotune",
            "--autotune-log-file", "/tmp/at.csv", "--verbose",
            "--start-timeout", "60", "--output-filename", "/tmp/logs",
            "--log-level", "DEBUG", "python", "train.py", "--lr", "0.1",
        ])
        s = make_settings(args)
        assert s.num_proc == 8
        assert [h.hostname for h in s.hosts] == ["a", "b"]
        assert s.timeline_filename == "/tmp/t.json"
        assert s.fusion_threshold_mb == 32
        assert s.cycle_time_ms == 3.5
        assert s.cache_capacity == 2048
        assert s.autotune and s.autotune_log_file == "/tmp/at.csv"
        assert s.command == ["python", "train.py", "--lr", "0.1"]

    def test_elastic_flags(self):
        args = parse_args([
            "--min-np", "2", "--max-np", "4",
            "--host-discovery-script", "/tmp/discover.sh", "--slots", "1",
            "python", "train.py"])
        s = make_settings(args)
        assert s.elastic
        assert s.min_np == 2 and s.max_np == 4 and s.slots_per_host == 1

    def test_backend_selectors_accepted(self):
        # --gloo/--mpi accepted for drop-in compat, ignored.
        args = parse_args(["-np", "2", "--gloo", "python", "x.py"])
        assert args.np == 2

    def test_check_build_output(self):
        out = check_build()
        assert "XLA collectives" in out
        assert "elastic" in out and "adasum" in out


# ---------------------------------------------------------------------------
# Rendezvous KV store (reference: test_http_server.py)
# ---------------------------------------------------------------------------

class TestRendezvous:
    @pytest.fixture(params=["python", "native"])
    def server(self, request):
        if request.param == "native":
            from horovod_tpu._native import load
            if load() is None:
                pytest.skip("native control plane not available")
        srv = RendezvousServer(prefer_native=(request.param == "native"))
        port = srv.start()
        if request.param == "native":
            assert srv._native is not None, "native engine did not engage"
        yield srv, port
        srv.stop()

    def _client(self, server):
        srv, port = server
        return RendezvousClient("127.0.0.1", port, srv.secret)

    def test_put_get(self, server):
        c = self._client(server)
        assert c.get("missing") is None
        c.put("k", "v")
        assert c.get("k") == "v"
        c.put("k", "v2")
        assert c.get("k") == "v2"

    def test_delete_keys(self, server):
        c = self._client(server)
        c.put("a/1", "x")
        c.put("a/2", "y")
        c.put("b/1", "z")
        assert c.keys("a/") == ["a/1", "a/2"]
        assert c.delete("a/1")
        assert not c.delete("a/1")
        assert c.keys("a/") == ["a/2"]

    def test_wait_blocks_until_put(self, server):
        c = self._client(server)
        result = {}

        def waiter():
            result["v"] = c.wait("later", timeout=10)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        c.put("later", "arrived")
        t.join(timeout=10)
        assert result["v"] == "arrived"

    def test_wait_timeout(self, server):
        c = self._client(server)
        with pytest.raises(HorovodTpuError):
            c.wait("never", timeout=0.3)

    def test_barrier(self, server):
        c = self._client(server)
        n, reached = 3, []

        def enter(i):
            self._client(server).barrier("b1", n, timeout=10)
            reached.append(i)

        threads = [threading.Thread(target=enter, args=(i,))
                   for i in range(n - 1)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        assert reached == []  # nobody through until the last arrives
        c.barrier("b1", n, timeout=10)
        for t in threads:
            t.join(timeout=10)
        assert sorted(reached) == [0, 1]

    def test_barrier_timeout(self, server):
        c = self._client(server)
        with pytest.raises(HorovodTpuError):
            c.barrier("alone", 2, timeout=0.3)

    def test_hmac_rejects_wrong_secret(self, server):
        srv, port = server
        bad = RendezvousClient("127.0.0.1", port, new_secret(),
                               connect_retries=1)
        with pytest.raises(HorovodTpuError):
            bad.put("k", "v")

    def test_ping(self, server):
        assert self._client(server).ping()


# ---------------------------------------------------------------------------
# End-to-end launch over localhost (reference: test_static_run.py)
# ---------------------------------------------------------------------------

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(cli_args, timeout=120):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    # Workers must not inherit the test process's TPU/device pinning.
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner"] + cli_args,
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO_ROOT)


class TestStaticRun:
    def test_check_build_cli(self):
        r = _run_cli(["--check-build"])
        assert r.returncode == 0
        assert "XLA collectives" in r.stdout

    def test_no_command_errors(self):
        r = _run_cli(["-np", "2"])
        assert r.returncode == 2

    def test_single_proc_env_injection(self, tmp_path):
        script = tmp_path / "w.py"
        script.write_text(
            "import os\n"
            "print('RANK=%s SIZE=%s LOCAL=%s' % ("
            "os.environ['HOROVOD_RANK'], os.environ['HOROVOD_SIZE'],"
            "os.environ['HOROVOD_LOCAL_RANK']))\n")
        r = _run_cli(["-np", "1", sys.executable, str(script)])
        assert r.returncode == 0, r.stderr
        assert "RANK=0 SIZE=1 LOCAL=0" in r.stdout

    def test_two_proc_rendezvous(self, tmp_path):
        # Two workers coordinate through the control-plane KV store.
        script = tmp_path / "w.py"
        script.write_text(
            "import os\n"
            "from horovod_tpu.runner.rendezvous import RendezvousClient\n"
            "rank = os.environ['HOROVOD_RANK']\n"
            "c = RendezvousClient(os.environ['HOROVOD_RENDEZVOUS_ADDR'],\n"
            "    int(os.environ['HOROVOD_RENDEZVOUS_PORT']),\n"
            "    os.environ['HOROVOD_SECRET_KEY'])\n"
            "c.put('hello/' + rank, 'from-' + rank)\n"
            "c.barrier('done', 2, timeout=60)\n"
            "other = '1' if rank == '0' else '0'\n"
            "assert c.get('hello/' + other) == 'from-' + other\n"
            "print('rank %s ok' % rank)\n")
        r = _run_cli(["-np", "2", sys.executable, str(script)])
        assert r.returncode == 0, r.stderr
        assert "rank 0 ok" in r.stdout and "rank 1 ok" in r.stdout

    def test_failure_propagates(self, tmp_path):
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['HOROVOD_RANK'] == '1':\n"
            "    sys.exit(3)\n"
            "time.sleep(60)\n")
        t0 = time.time()
        r = _run_cli(["-np", "2", sys.executable, str(script)])
        # Rank 1 fails; the launcher must kill rank 0 and exit nonzero
        # well before rank 0's 60s sleep finishes.
        assert r.returncode != 0
        assert time.time() - t0 < 45


class TestRunAPI:
    def test_run_func(self):
        # Top-level function so it pickles.
        from horovod_tpu.runner import run
        results = run(_rank_times_two, np=2)
        assert results == [0, 2]


def _rank_times_two():
    import os
    return int(os.environ["HOROVOD_RANK"]) * 2


class TestRunAPIFullSignature:
    """Reference horovod.run's flag surface: hostfile, elastic routing,
    compat no-op backend selectors."""

    def test_run_with_hostfile(self, tmp_path, monkeypatch):
        monkeypatch.delenv("XLA_FLAGS", raising=False)
        hf = tmp_path / "hosts"
        hf.write_text("localhost slots=2\n")
        from horovod_tpu.runner import run
        assert run(_rank_times_two, np=2, hostfile=str(hf),
                   use_gloo=True, use_mpi=False) == [0, 2]

    def test_run_elastic_via_discovery_script(self, tmp_path, monkeypatch):
        monkeypatch.delenv("XLA_FLAGS", raising=False)
        hosts = tmp_path / "h.txt"
        hosts.write_text("localhost:2\n")
        script = tmp_path / "d.sh"
        script.write_text(f"#!/bin/sh\ncat {hosts}\n")
        script.chmod(0o755)
        from horovod_tpu.runner import run
        out = run(_rank_times_two, np=2, min_np=2, slots=2,
                  host_discovery_script=str(script))
        assert sorted(out) == [0, 2]

    def test_conflicting_host_sources_rejected(self):
        from horovod_tpu.runner import run
        with pytest.raises(ValueError, match="conflict"):
            run(_rank_times_two, np=2, hosts="a:2",
                host_discovery_script="/bin/true")
        with pytest.raises(ValueError, match="not both"):
            run(_rank_times_two, np=2, hosts="a:2", hostfile="/tmp/hf")
