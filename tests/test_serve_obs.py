"""Serving-observability tests (the request-level forensics surface):
lifecycle spans on per-request timeline lanes (ordering, abutment, the
queue+prefill+decode == e2e decomposition `trace analyze --serve`
reports), the serving latency histograms against hand-computed bucket
counts, the flight recorder's ring bounds / trigger matrix / atomic
dump, and the two-replica e2e where a `serve.replica_die` fault leaves
a loadable dump and the trace merge stitches the reassigned request's
lane across replicas."""

import bisect
import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.metrics import catalog as _met
from horovod_tpu.metrics.registry import Histogram, default_latency_buckets
from horovod_tpu.models import TransformerConfig, transformer_init
from horovod_tpu.serve import FlightRecorder, InferenceServer, PoolExhaustedError
from horovod_tpu.serve import flightrec as flightrec_mod
from horovod_tpu.serve.loadgen import hist_cumulative, hist_delta_quantile
from horovod_tpu.trace import core as trace_core
from horovod_tpu.utils import autotune
from horovod_tpu.utils.timeline import start_timeline, stop_timeline


def _cfg(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=4, d_head=8,
                d_ff=64, n_layers=2, compute_dtype=jnp.float32)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return cfg, transformer_init(jax.random.PRNGKey(0), cfg)


def _server(model, **kw):
    cfg, params = model
    kw.setdefault("max_seq_tokens", 24)
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_tokens", 4)
    return InferenceServer(params, cfg, **kw)


def _submit_some(srv, n=3, seed=2):
    rng = np.random.RandomState(seed)
    return [srv.submit(rng.randint(0, 64, size=4).tolist(),
                       int(rng.randint(2, 5))) for _ in range(n)]


def _req_lanes(events):
    lanes = {}
    for ev in events:
        tid = str(ev.get("tid", ""))
        if str(ev.get("cat", "")) == "serve" and tid.startswith("req/"):
            lanes.setdefault(tid, []).append(ev)
    return lanes


class TestLifecycleSpans:
    # Stamp-gap tolerance (us) between abutting spans: the gaps are
    # pure host bookkeeping between two `now_us()` reads, but a loaded
    # CI machine can preempt between them.
    TOL_US = 50_000.0

    def _run_traced(self, model, tmp_path, monkeypatch, n=3):
        monkeypatch.setenv("HOROVOD_SERVE_FLIGHTREC_DIR", str(tmp_path))
        tlf = str(tmp_path / "serve_tl.json")
        start_timeline(tlf)
        try:
            srv = _server(model)
            ids = _submit_some(srv, n=n)
            done = srv.run()
        finally:
            stop_timeline()
        assert len(done) == n
        return trace_core.load_events(tlf), ids

    def test_span_ordering_and_abutment(self, model, tmp_path, monkeypatch):
        events, ids = self._run_traced(model, tmp_path, monkeypatch)
        lanes = _req_lanes(events)
        assert set(lanes) == {f"req/{i}" for i in ids}
        for tid, evs in lanes.items():
            spans = {e["name"]: e for e in evs if e.get("ph") == "X"}
            inst = {e["name"]: e for e in evs if e.get("ph") == "i"}
            assert set(spans) == {"queue_wait", "prefill", "decode"}
            assert set(inst) == {"serve_submit", "serve_first_token",
                                 "serve_evict"}
            sub = float(inst["serve_submit"]["ts"])
            qw, pf, dec = (spans[n] for n in
                           ("queue_wait", "prefill", "decode"))
            qw_s, qw_e = float(qw["ts"]), float(qw["ts"]) + float(qw["dur"])
            pf_s, pf_e = float(pf["ts"]), float(pf["ts"]) + float(pf["dur"])
            dc_s, dc_e = float(dec["ts"]), float(dec["ts"]) + float(dec["dur"])
            # Lifecycle order: submit opens the queue_wait span, which
            # abuts prefill, which abuts decode; first token falls
            # inside decode; evict marks the end.
            assert abs(qw_s - sub) <= self.TOL_US
            assert qw_e - self.TOL_US <= pf_s <= qw_e + self.TOL_US
            assert pf_e - self.TOL_US <= dc_s <= pf_e + self.TOL_US
            ft = float(inst["serve_first_token"]["ts"])
            assert dc_s - self.TOL_US <= ft <= dc_e + self.TOL_US
            assert float(inst["serve_evict"]["ts"]) >= dc_e - self.TOL_US
            # The decomposition invariant: components sum to e2e within
            # the stamp gaps.
            e2e = dc_e - sub
            total = (qw_e - qw_s) + (pf_e - pf_s) + (dc_e - dc_s)
            assert abs(e2e - total) <= 3 * self.TOL_US

    def test_analyze_serve_decomposition_sums(self, model, tmp_path,
                                              monkeypatch):
        events, ids = self._run_traced(model, tmp_path, monkeypatch)
        report = trace_core.analyze_serve({0: events}, align="wall")
        assert report["summary"]["requests"] == len(ids)
        assert report["summary"]["completed"] == len(ids)
        assert report["summary"]["reassigned"] == 0
        for row in report["requests"]:
            assert row["complete"] and not row["reassigned"]
            parts = row["queue_ms"] + row["prefill_ms"] + row["decode_ms"]
            assert abs(parts - row["e2e_ms"]) <= 3 * self.TOL_US / 1e3
            assert row["spec_verify_ms"] >= 0.0
            assert row["ttft_ms"] is not None
            assert 0.0 <= row["ttft_ms"] <= row["e2e_ms"] + self.TOL_US / 1e3

    def test_analyze_serve_reassignment_blame(self):
        """Synthetic two-replica lanes: the pid owning `decode`
        completed; the other pid that saw the lane is blamed."""
        def span(pid, name, ts, dur, args=None):
            return {"ph": "X", "cat": "serve", "name": name, "pid": pid,
                    "tid": "req/7", "ts": ts, "dur": dur,
                    "args": args or {}}

        def inst(pid, name, ts):
            return {"ph": "i", "cat": "serve", "name": name, "pid": pid,
                    "tid": "req/7", "ts": ts, "s": "t"}

        traces = {
            0: [inst(0, "serve_submit", 1000.0),
                span(0, "queue_wait", 1000.0, 500.0),
                span(0, "prefill", 1500.0, 300.0),
                inst(0, "serve_first_token", 2000.0),
                span(0, "decode", 1800.0, 700.0,
                     {"tokens": 4, "spec_ms": 0.1}),
                inst(0, "serve_evict", 2500.0)],
            # The dead replica saw the request first: partial lane only.
            1: [inst(1, "serve_submit", 100.0),
                span(1, "queue_wait", 100.0, 200.0)],
        }
        report = trace_core.analyze_serve(traces, align="wall")
        (row,) = report["requests"]
        assert row["reassigned"] and row["replicas"] == [0, 1]
        assert row["completed_by"] == 0 and row["blamed_replica"] == 1
        assert row["e2e_ms"] == pytest.approx(1.5)
        assert row["queue_ms"] + row["prefill_ms"] + row["decode_ms"] == \
            pytest.approx(row["e2e_ms"])
        assert report["summary"]["reassigned"] == 1
        # merge draws the cross-replica flow arrow for exactly this lane
        merged = trace_core.merge(traces, align="wall", flow=True)
        flows = [e for e in merged["traceEvents"]
                 if e.get("cat") == "xrank" and
                 str(e.get("tid", "")).startswith("req/")]
        assert {"s", "f"} <= {e["ph"] for e in flows}
        assert {e["pid"] for e in flows} == {0, 1}


class TestLatencyHistograms:
    def test_bucket_counts_match_hand_computed(self):
        h = Histogram("test_obs_hand_hist_seconds", "test-only")
        lats = [5e-7, 2e-6, 3.9e-6, 1e-4, 2.5e-3, 0.5, 70.0]
        for v in lats:
            h.observe(v)
        bounds = default_latency_buckets()
        counts = [0] * (len(bounds) + 1)
        for v in lats:
            counts[bisect.bisect_left(bounds, v)] += 1
        expect, running = [], 0
        for b, c in zip(bounds, counts):
            running += c
            expect.append((b, running))
        expect.append((float("inf"), running + counts[-1]))
        assert h._solo().cumulative() == expect

    def test_hist_delta_quantile_ignores_prior_observations(self):
        h = Histogram("test_obs_delta_hist_seconds", "test-only")
        h.observe(50.0)                      # pre-snapshot contamination
        before = hist_cumulative(h)
        for _ in range(100):
            h.observe(3e-6)
        after = hist_cumulative(h)
        for q in (50.0, 99.0):
            v = hist_delta_quantile(before, after, q)
            assert 1e-6 <= v <= 4e-6         # inside the containing bucket
        assert hist_delta_quantile(before, before, 50.0) == 0.0

    def test_server_observes_serving_histograms(self, model, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_FLIGHTREC_DIR", str(tmp_path))
        hists = (_met.serve_ttft, _met.serve_queue_delay,
                 _met.serve_e2e_latency, _met.serve_intertoken)
        before = [hist_cumulative(h) for h in hists]
        srv = _server(model)
        n = len(_submit_some(srv, n=3))
        done = srv.run()
        assert len(done) == n
        after = [hist_cumulative(h) for h in hists]
        deltas = [a[-1][1] - b[-1][1] for a, b in zip(after, before)]
        # One TTFT / queue-delay / e2e observation per request; at least
        # one inter-token observation per decode step that decided any.
        assert deltas[0] == n and deltas[1] == n and deltas[2] == n
        assert deltas[3] >= 1
        # All e2e observations are positive and sane (<67s top bucket).
        e2e_p99 = hist_delta_quantile(before[2], after[2], 99.0)
        assert 0.0 < e2e_p99 < 67.0

    def test_metrics_interval_env_knob(self, model, monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_METRICS_INTERVAL", "5")
        assert _server(model)._metrics_interval == 5
        monkeypatch.setenv("HOROVOD_SERVE_METRICS_INTERVAL", "0")
        assert _server(model)._metrics_interval == 1   # clamped
        monkeypatch.delenv("HOROVOD_SERVE_METRICS_INTERVAL")
        assert _server(model)._metrics_interval == 16  # default

    def test_flush_at_drain_exports_final_gauges(self, model, tmp_path,
                                                 monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_FLIGHTREC_DIR", str(tmp_path))
        # Interval larger than the run: only the drain flush samples.
        monkeypatch.setenv("HOROVOD_SERVE_METRICS_INTERVAL", "100000")
        srv = _server(model)
        _submit_some(srv, n=2)
        srv.run()
        assert _met.serve_queue_depth._solo()._value == 0.0


class TestFlightRecorder:
    def test_ring_bounds_and_drop_count(self, tmp_path):
        rec = FlightRecorder(8, out_dir=str(tmp_path))
        try:
            for i in range(20):
                rec.record("step", {"i": i}, step=i)
            assert len(rec) == 8
            assert [e["seq"] for e in rec.snapshot()] == list(range(12, 20))
            path = rec.dump("manual")
            payload = flightrec_mod.load_dump(path)
            assert payload["recorded_total"] == 20
            assert payload["dropped"] == 12
            assert len(payload["events"]) == 8
        finally:
            rec.close()

    def test_depth_below_one_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            FlightRecorder(0, out_dir=str(tmp_path))

    def test_dump_is_atomic_and_loadable(self, tmp_path):
        rec = FlightRecorder(4, out_dir=str(tmp_path))
        try:
            rec.record("slo", {"event": "spec_on"}, step=3)
            rec.record("span", {"name": "prefill", "req": 1},
                       ts_us=10.0, dur_us=5.0)
            path = rec.dump("manual")
            assert os.path.basename(path).startswith("serve_flightrec.")
            assert not glob.glob(str(tmp_path / "*.tmp"))  # no torn temp
            payload = trace_core.load_flightrec(path)
            trace = trace_core.flightrec_to_trace(payload)
            phs = {e.get("ph") for e in trace["traceEvents"]}
            assert "X" in phs and "i" in phs
            span = next(e for e in trace["traceEvents"]
                        if e.get("ph") == "X")
            assert span["tid"] == "req/1" and span["dur"] == 5.0
        finally:
            rec.close()

    def test_load_dump_rejects_non_dumps(self, tmp_path):
        bad = tmp_path / "not_a_dump.json"
        bad.write_text(json.dumps([1, 2, 3]))
        with pytest.raises(ValueError):
            flightrec_mod.load_dump(str(bad))

    def test_server_feeds_ring(self, model, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_FLIGHTREC_DIR", str(tmp_path))
        srv = _server(model)
        assert srv.flightrec is not None
        _submit_some(srv, n=2)
        srv.run()
        kinds = {e["kind"] for e in srv.flightrec.snapshot()}
        assert {"step", "span", "pool", "first_token"} <= kinds

    def test_depth_env_disables(self, model, monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_FLIGHTREC_DEPTH", "0")
        assert _server(model).flightrec is None

    def test_step_crash_triggers_dump(self, model, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_FLIGHTREC_DIR", str(tmp_path))
        srv = _server(model)
        _submit_some(srv, n=1)

        def boom():
            raise PoolExhaustedError("out of pages")
        monkeypatch.setattr(srv, "_step_impl", boom)
        with pytest.raises(PoolExhaustedError):
            srv.step()
        payload = flightrec_mod.load_dump(srv.flightrec.dumps[-1])
        assert payload["reason"] == "pool_exhausted"
        assert payload["events"][-1]["kind"] == "error"

    def test_step_crash_reason_carries_type(self, model, tmp_path,
                                            monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_FLIGHTREC_DIR", str(tmp_path))
        srv = _server(model)

        def boom():
            raise ValueError("bad state")
        monkeypatch.setattr(srv, "_step_impl", boom)
        with pytest.raises(ValueError):
            srv.step()
        payload = flightrec_mod.load_dump(srv.flightrec.dumps[-1])
        assert payload["reason"] == "crash:ValueError"

    def test_slo_breach_triggers_dump(self, model, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_FLIGHTREC_DIR", str(tmp_path))
        srv = _server(model, slo_ms=5.0)
        srv.slo.record(100.0)
        assert srv.slo.update(0) is True     # p99 over budget: spec_on
        payload = flightrec_mod.load_dump(srv.flightrec.dumps[-1])
        assert payload["reason"] == "slo_breach"
        assert any(e["kind"] == "slo" and e["data"]["event"] == "spec_on"
                   for e in payload["events"])

    def test_fault_exit_hook_triggers_dump(self, tmp_path, monkeypatch):
        """The `exit` fault mode bypasses atexit (`os._exit`); the
        recorder must dump through faults.register_exit_hook before the
        process dies.  os._exit is stubbed out so the trigger path runs
        to completion in-process."""
        import horovod_tpu.faults as faults
        from horovod_tpu.faults import spec as fspec
        exits = []
        monkeypatch.setattr(fspec.os, "_exit", exits.append)
        rec = FlightRecorder(4, out_dir=str(tmp_path))
        rec.record("step", {"rows": 1}, step=0)
        faults.install("serve.replica_die:exit:1")
        try:
            faults.point("serve.replica_die")
        finally:
            faults.clear()
            rec.close()
        assert exits == [1]
        payload = flightrec_mod.load_dump(rec.dumps[-1])
        assert payload["reason"] == "fault_exit:serve.replica_die"

    def test_dump_all_never_raises(self, tmp_path):
        good = FlightRecorder(4, out_dir=str(tmp_path))
        broken = FlightRecorder(4, out_dir=str(tmp_path / "missing_dir"))
        good.record("step", {}, step=0)
        try:
            paths = flightrec_mod.dump_all("guard_escalation")
        finally:
            good.close()
            broken.close()
        assert good.dumps and good.dumps[-1] in paths
        assert not broken.dumps               # failed silently, by design
        payload = flightrec_mod.load_dump(good.dumps[-1])
        assert payload["reason"] == "guard_escalation"


class TestFlightrecAutotuneKnob:
    def test_host_only_knob_excluded_from_values(self):
        pm = autotune.ParameterManager()
        pm.register("fusion_threshold", 1 << 20, 256 << 20,
                    log_scale=True, integer=True)
        pm.register("serve_flightrec_depth", 64, 8192, log_scale=True,
                    integer=True, host_only=True, initial=512)
        vals = pm.values()
        # values() keys the program cache: the host-only depth must
        # never appear there, but stays individually readable.
        assert "serve_flightrec_depth" not in vals
        assert "fusion_threshold" in vals
        assert pm.value("serve_flightrec_depth") == 512

    def test_current_depth_env(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_FLIGHTREC_DEPTH", "7")
        assert autotune.current_serve_flightrec_depth() == 7
        monkeypatch.setenv("HOROVOD_SERVE_FLIGHTREC_DEPTH", "-1")
        assert autotune.current_serve_flightrec_depth() == 0
        monkeypatch.delenv("HOROVOD_SERVE_FLIGHTREC_DEPTH")
        assert autotune.current_serve_flightrec_depth() == 512


@pytest.mark.slow
class TestServeObsE2E:
    """Two serving replicas; the serve.replica_die fault kills replica1
    mid-stream.  The dead incarnation must leave a loadable
    flight-recorder dump (via the fault-exit hook) that converts to
    Perfetto, and the per-replica timelines must merge into a trace
    where the reassigned requests' lanes span both replicas."""

    CONFIG = {
        "cfg": dict(vocab_size=64, d_model=32, n_heads=4, d_head=8,
                    d_ff=64, n_layers=2, compute_dtype="float32"),
        "seed": 0,
        "serve": dict(max_seq_tokens=24, max_batch=2, page_tokens=4),
    }

    def test_replica_death_dump_and_stitched_trace(self, tmp_path):
        from horovod_tpu.serve.replica import ReplicaManager
        tl_base = str(tmp_path / "serve_tl.json")
        env = {
            "JAX_PLATFORMS": "cpu",
            "HOROVOD_TIMELINE": tl_base,
            "HOROVOD_SERVE_FLIGHTREC_DIR": str(tmp_path),
            "HOROVOD_FAULT_SPEC": "serve.replica_die@3:exit:1",
            "HOROVOD_FAULT_HOSTS": "replica1",
        }
        rng = np.random.RandomState(1)
        reqs = [(rng.randint(0, 64, size=4).tolist(),
                 int(rng.randint(2, 6))) for _ in range(6)]
        with ReplicaManager(2, self.CONFIG, lease_ttl=10.0,
                            respawn_backoff=0.2, child_env=env) as mgr:
            for prompt, mn in reqs:
                mgr.submit(prompt, mn)
            results = mgr.wait_all(timeout=180)
            respawns = mgr._respawns
        assert len(results) == 6
        assert respawns >= 1

        # 1. The dead replica dumped its ring through the fault-exit
        # hook before os._exit.
        dumps = sorted(glob.glob(
            str(tmp_path / "serve_flightrec.replica1.*.json")))
        assert dumps, "dead replica left no flight-recorder dump"
        payload = flightrec_mod.load_dump(dumps[0])
        assert payload["reason"] == "fault_exit:serve.replica_die"
        assert payload["replica"] == 1
        assert payload["events"]

        # 2. The dump converts to a valid Perfetto trace.
        trace = trace_core.flightrec_to_trace(payload)
        evs = trace["traceEvents"]
        assert evs and all(e.get("pid") == 1 for e in evs
                           if e.get("ph") in ("X", "i"))
        json.dumps(trace)                     # fully serializable

        # 3. The per-replica timelines (the dead incarnation's file
        # survives the respawn under .respawn<k>) merge into one trace
        # where at least one reassigned request's lane spans both
        # replicas and carries the cross-replica flow arrow.
        files = sorted(glob.glob(tl_base + ".rank*"))
        assert len(files) >= 2
        report = trace_core.analyze_serve(files, align="wall")
        assert report["summary"]["completed"] == 6
        stitched = [r for r in report["requests"] if r["reassigned"]]
        assert stitched, "no request lane spans both replicas"
        for row in stitched:
            assert row["blamed_replica"] == 1
            assert row["completed_by"] is not None
        merged = trace_core.merge(files, align="wall", flow=True)
        flow_tids = {e["tid"] for e in merged["traceEvents"]
                     if e.get("cat") == "xrank" and
                     str(e.get("tid", "")).startswith("req/")}
        assert {f"req/{r['req']}" for r in stitched} <= flow_tids
