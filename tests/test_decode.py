"""KV-cache incremental decoding tests (models/decode.py): every
decode-step logit must equal the full teacher-forcing forward at that
position — the exact consistency contract between the training and
inference paths — across MHA, GQA, and windowed configs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import (
    TransformerConfig,
    init_decode_cache,
    transformer_decode_step,
    transformer_generate,
    transformer_init,
    transformer_prefill,
    transformer_ref_apply,
)


def _cfg(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=4, d_head=8,
                d_ff=64, n_layers=2, compute_dtype=jnp.float32)
    base.update(kw)
    return TransformerConfig(**base)


class TestDecodeStep:
    @pytest.mark.parametrize("kw", [
        {}, {"n_kv_heads": 2}, {"n_kv_heads": 1},
        {"n_kv_heads": 2, "attn_window": 5}, {"attn_window": 3},
    ], ids=["mha", "gqa2", "mqa", "gqa+window", "window"])
    def test_matches_teacher_forcing(self, kw):
        cfg = _cfg(**kw)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 64)
        full_logits, _ = transformer_ref_apply(params, toks, cfg)
        cache = init_decode_cache(cfg, 2, 12)
        step = jax.jit(
            lambda c, t: transformer_decode_step(params, c, t, cfg))
        for t in range(12):
            lg, cache = step(cache, toks[:, t])
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full_logits[:, t]),
                atol=2e-4, rtol=2e-4, err_msg=f"position {t}")
        assert int(cache["pos"]) == 12

    def test_gqa_cache_is_smaller(self):
        big = init_decode_cache(_cfg(), 2, 16)
        small = init_decode_cache(_cfg(n_kv_heads=1), 2, 16)
        assert small["k"].size * 4 == big["k"].size

    def test_moe_decode_matches_teacher_forcing(self):
        # capacity_factor = n_experts -> training capacity drops nothing,
        # so the no-capacity decode routing must match the training
        # forward exactly.
        cfg = _cfg(moe_every=2, n_experts=4, capacity_factor=4.0)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        full, _ = transformer_ref_apply(params, toks, cfg)
        cache = init_decode_cache(cfg, 2, 8)
        step = jax.jit(
            lambda c, t: transformer_decode_step(params, c, t, cfg))
        for t in range(8):
            lg, cache = step(cache, toks[:, t])
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t]),
                atol=3e-4, rtol=3e-4, err_msg=f"position {t}")

    def test_moe_prefill_matches_teacher_forcing(self):
        from horovod_tpu.models import transformer_prefill

        cfg = _cfg(moe_every=2, n_experts=4, capacity_factor=4.0)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(5), (2, 8), 0, 64)
        full, _ = transformer_ref_apply(params, toks, cfg)
        cache = init_decode_cache(cfg, 2, 8)
        logits, cache = transformer_prefill(params, cache, toks, cfg)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, -1]),
                                   atol=3e-4, rtol=3e-4)

    def test_moe_generate_runs(self):
        cfg = _cfg(moe_every=2, n_experts=2)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 3), 0, 64)
        out, cache = transformer_generate(params, cfg, prompt, 5)
        assert out.shape == (1, 5) and int(cache["pos"]) == 8
        assert bool((out >= 0).all()) and bool((out < 64).all())


class TestGenerate:
    def test_greedy_chain_consistent(self):
        # Teacher-forcing the generated sequence reproduces the same
        # greedy choices the incremental path made.
        cfg = _cfg(n_kv_heads=2)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, 64)
        out, cache = transformer_generate(params, cfg, prompt,
                                          max_new_tokens=6)
        assert out.shape == (2, 6) and int(cache["pos"]) == 10
        seq = jnp.concatenate([prompt, out], axis=1)
        logits, _ = transformer_ref_apply(params, seq, cfg)
        want = jnp.argmax(logits[:, 3:-1], axis=-1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(want))

    def test_sampling_needs_rng_and_runs(self):
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((1, 2), jnp.int32)
        with pytest.raises(ValueError, match="rng"):
            transformer_generate(params, cfg, prompt, 3, temperature=1.0)
        out, _ = transformer_generate(params, cfg, prompt, 3,
                                      temperature=1.0,
                                      rng=jax.random.PRNGKey(0))
        assert out.shape == (1, 3)
        assert bool((out >= 0).all()) and bool((out < 64).all())

    def test_max_len_validation(self):
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="max_len"):
            transformer_generate(params, cfg, prompt, 8, max_len=8)


def _assert_greedy_equiv(params, cfg, prompt, spec, plain, tol=5e-4):
    """Greedy equivalence up to numerical near-ties: the speculative
    chain must match the plain chain token-for-token UNLESS the first
    divergence sits on a near-tie in the target's own teacher-forced
    logits (top-2 gap within `tol`) — the chunked verify pass and the
    step-by-step chain reduce the same floats in different orders, so
    they may legitimately break an exact-noise tie differently.  Both
    chains condition on their own history after that point, so
    comparison for that row stops at the first near-tie divergence."""
    spec, plain = np.asarray(spec), np.asarray(plain)
    for b in range(spec.shape[0]):
        if (spec[b] == plain[b]).all():
            continue
        first = int(np.argmax(spec[b] != plain[b]))
        seq = jnp.concatenate(
            [prompt[b], jnp.asarray(plain[b][:first])])[None]
        logits, _ = transformer_ref_apply(params, seq, cfg)
        last = np.asarray(logits[0, -1], np.float32)
        top2 = np.sort(last)[-2:]
        gap = float(top2[1] - top2[0])
        assert gap <= tol, (
            f"row {b} diverges at new-token {first} with a clear "
            f"argmax (top-2 logit gap {gap:.2e} > tol {tol}): "
            f"spec={spec[b, first]} plain={plain[b, first]}")
        tied = np.flatnonzero(last >= top2[1] - tol)
        assert spec[b, first] in tied and plain[b, first] in tied, (
            b, first, spec[b, first], plain[b, first], tied)


class TestChunkExtendAndSpeculative:
    """transformer_extend (multi-token chunks) and speculative decoding
    (r5, beyond reference: draft-propose / target-verify with greedy
    equivalence up to numerical near-ties)."""

    def test_extend_matches_stepwise_decode(self):
        from horovod_tpu.models import transformer_extend

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, 64)
        toks = jax.random.randint(jax.random.PRNGKey(2), (2, 3), 0, 64)

        c1 = init_decode_cache(cfg, 2, 16)
        _, c1 = transformer_prefill(params, c1, prompt, cfg)
        lg_chunk, c1 = transformer_extend(params, c1, toks, cfg)

        c2 = init_decode_cache(cfg, 2, 16)
        _, c2 = transformer_prefill(params, c2, prompt, cfg)
        step_lgs = []
        for i in range(3):
            lg, c2 = transformer_decode_step(params, c2, toks[:, i], cfg)
            step_lgs.append(lg)
        np.testing.assert_allclose(
            np.asarray(lg_chunk), np.stack(
                [np.asarray(s) for s in step_lgs], axis=1),
            rtol=2e-5, atol=2e-5)
        assert int(c1["pos"]) == int(c2["pos"]) == 7

    def test_extend_gqa_and_quantized_cache(self):
        from horovod_tpu.models import transformer_extend

        cfg = _cfg(n_kv_heads=2)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, 64)
        toks = jax.random.randint(jax.random.PRNGKey(2), (1, 2), 0, 64)
        for quant in (None, "int8"):
            c = init_decode_cache(cfg, 1, 12, quantize=quant)
            _, c = transformer_prefill(params, c, prompt, cfg)
            lg, c = transformer_extend(params, c, toks, cfg)
            assert lg.shape == (1, 2, 64)
            assert np.isfinite(np.asarray(lg)).all()

    def test_extend_wrap_rejected(self):
        from horovod_tpu.models import transformer_extend

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, 64)
        c = init_decode_cache(cfg, 1, 6)
        _, c = transformer_prefill(params, c, prompt, cfg)
        toks = jax.random.randint(jax.random.PRNGKey(2), (1, 3), 0, 64)
        with pytest.raises(ValueError, match="wrap"):
            transformer_extend(params, c, toks, cfg)

    def test_extend_on_wrapped_windowed_ring_rejected(self):
        # Past max_len on a WINDOWED config the chunk's slot-position
        # reconstruction anchors at its last query, so earlier queries
        # would silently attend over a truncated window — rejected
        # eagerly, even for a chunk that would not wrap the ring.
        from horovod_tpu.models import transformer_extend

        cfg = _cfg(attn_window=3)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        tok = jnp.zeros((1,), jnp.int32)
        c = init_decode_cache(cfg, 1, 4)
        for _ in range(4):                      # fill to pos == max_len
            _, c = transformer_decode_step(params, c, tok, cfg)
        assert int(c["pos"]) == 4
        chunk = jnp.zeros((1, 2), jnp.int32)    # pos%S + 2 <= S: no wrap
        with pytest.raises(ValueError, match="attn_window"):
            transformer_extend(params, c, chunk, cfg)
        # The same chunk on a WINDOWLESS config is legal (ring reuse is
        # the caller's contract there) — the rejection is window-specific.
        cfg2 = _cfg()
        c2 = init_decode_cache(cfg2, 1, 4)
        params2 = transformer_init(jax.random.PRNGKey(0), cfg2)
        for _ in range(4):
            _, c2 = transformer_decode_step(params2, c2, tok, cfg2)
        lg, _ = transformer_extend(params2, c2, chunk, cfg2)
        assert lg.shape == (1, 2, 64)

    def test_speculative_greedy_matches_plain_generate(self):
        from horovod_tpu.models import transformer_speculative_generate

        cfg = _cfg(n_layers=2)
        draft_cfg = _cfg(d_model=16, n_heads=2, d_head=8, d_ff=32,
                         n_layers=1)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        draft = transformer_init(jax.random.PRNGKey(7), draft_cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0, 64)

        plain, _ = transformer_generate(params, cfg, prompt, 12)
        spec, stats = transformer_speculative_generate(
            params, cfg, draft, draft_cfg, prompt, 12, gamma=3)
        _assert_greedy_equiv(params, cfg, prompt, spec, plain)
        assert stats["rounds"] >= 1
        assert 0.0 <= stats["accept_rate"] <= 1.0

    def test_self_speculation_accepts_everything(self):
        # Draft == target: every greedy proposal matches, so each round
        # lands gamma accepted + 1 bonus token.
        from horovod_tpu.models import transformer_speculative_generate

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, 64)
        plain, _ = transformer_generate(params, cfg, prompt, 9)
        spec, stats = transformer_speculative_generate(
            params, cfg, params, cfg, prompt, 9, gamma=4)
        _assert_greedy_equiv(params, cfg, prompt, spec, plain)
        # Self-speculation agrees everywhere except genuine near-ties;
        # those are rare enough that the accept rate stays near 1.
        assert stats["accept_rate"] >= 0.9
        # 9 tokens at gamma=4: rounds of 4+1 -> ceil sizing, <= 3 rounds
        # barring a near-tie restart.
        assert stats["rounds"] <= 4

    @pytest.mark.parametrize("batch", [1, 3])
    def test_speculative_sampling_valid(self, batch):
        from horovod_tpu.models import transformer_speculative_generate

        cfg = _cfg()
        draft_cfg = _cfg(d_model=16, n_heads=2, d_head=8, d_ff=32,
                         n_layers=1)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        draft = transformer_init(jax.random.PRNGKey(7), draft_cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, 4),
                                    0, 64)
        toks, stats = transformer_speculative_generate(
            params, cfg, draft, draft_cfg, prompt, 8, gamma=3,
            temperature=0.8, rng=jax.random.PRNGKey(3))
        arr = np.asarray(toks)
        assert arr.shape == (batch, 8)
        assert ((arr >= 0) & (arr < 64)).all()

    def test_speculative_batched_matches_plain(self):
        # Min-acceptance batching: every row's output equals its own
        # target-greedy chain even when rows accept different lengths.
        from horovod_tpu.models import transformer_speculative_generate

        cfg = _cfg(n_layers=2)
        draft_cfg = _cfg(d_model=16, n_heads=2, d_head=8, d_ff=32,
                         n_layers=1)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        draft = transformer_init(jax.random.PRNGKey(7), draft_cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (3, 5), 0, 64)
        plain, _ = transformer_generate(params, cfg, prompt, 9)
        spec, stats = transformer_speculative_generate(
            params, cfg, draft, draft_cfg, prompt, 9, gamma=3)
        _assert_greedy_equiv(params, cfg, prompt, spec, plain)
        # Batched self-speculation: all rows agree (up to near-ties) ->
        # min acceptance is full and every round lands gamma+1 tokens.
        spec2, st2 = transformer_speculative_generate(
            params, cfg, params, cfg, prompt, 9, gamma=4)
        _assert_greedy_equiv(params, cfg, prompt, spec2, plain)
        assert st2["accept_rate"] >= 0.9

    def test_accept_rule_preserves_target_dist(self):
        # The identity speculative sampling rests on: draft ~ q, accept
        # with min(1, p/q), else resample from norm(max(p-q, 0)) ==>
        # emitted token ~ p EXACTLY.  Property-tested on the extracted
        # rule with synthetic distributions (50k trials, TV < 0.02;
        # a draft-vs-target TV of ~0.5 would fail at ~25x that bound
        # if the rule leaked the draft distribution).
        from horovod_tpu.models.decode import _spec_accept

        rng = np.random.default_rng(0)
        V = 8
        p = rng.dirichlet(np.ones(V) * 0.7)
        q = rng.dirichlet(np.ones(V) * 0.7)
        assert 0.5 * np.abs(p - q).sum() > 0.2   # distinct dists
        n = 50_000
        counts = np.zeros(V)
        accepted = 0
        for _ in range(n):
            d = int(rng.choice(V, p=q))
            ok, tok = _spec_accept(d, p, q, rng)
            counts[tok] += 1
            accepted += ok
        hist = counts / n
        tv = 0.5 * np.abs(hist - p).sum()
        assert tv < 0.02, tv
        # Acceptance probability equals sum min(p, q) in expectation.
        expect_acc = np.minimum(p, q).sum()
        assert abs(accepted / n - expect_acc) < 0.02

    def test_speculative_rejects_bad_configs(self):
        from horovod_tpu.models import transformer_speculative_generate

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, 64)
        wcfg = _cfg(attn_window=8)
        with pytest.raises(ValueError, match="attn_window"):
            transformer_speculative_generate(
                params, cfg, params, wcfg, prompt, 4)
        vcfg = _cfg(vocab_size=32)
        vparams = transformer_init(jax.random.PRNGKey(2), vcfg)
        with pytest.raises(ValueError, match="vocab"):
            transformer_speculative_generate(
                params, cfg, vparams, vcfg, prompt, 4)
        # Undersized explicit max_len must raise eagerly: inside jit the
        # ring-wrap guard cannot fire and the write would silently clamp.
        with pytest.raises(ValueError, match="max_len"):
            transformer_speculative_generate(
                params, cfg, params, cfg, prompt, 8, gamma=3,
                max_len=10)
        with pytest.raises(ValueError, match="temperature"):
            transformer_speculative_generate(
                params, cfg, params, cfg, prompt, 4, temperature=-1.0,
                rng=jax.random.PRNGKey(0))


class TestRingCacheAndPrefill:
    def test_prefill_matches_teacher_forcing(self):
        from horovod_tpu.models import transformer_prefill

        cfg = _cfg(n_kv_heads=2)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        full, _ = transformer_ref_apply(params, toks, cfg)
        cache = init_decode_cache(cfg, 2, 16)
        logits, cache = transformer_prefill(params, cache, toks, cfg)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, -1]),
                                   atol=2e-4, rtol=2e-4)
        assert int(cache["pos"]) == 10
        # decode continues seamlessly from the prefilled cache
        nxt = jnp.argmax(logits, axis=-1)
        lg2, cache = transformer_decode_step(params, cache, nxt, cfg)
        seq = jnp.concatenate([toks, nxt[:, None]], axis=1)
        full2, _ = transformer_ref_apply(params, seq, cfg)
        np.testing.assert_allclose(np.asarray(lg2),
                                   np.asarray(full2[:, -1]),
                                   atol=2e-4, rtol=2e-4)

    def test_ring_rolls_with_window(self):
        # max_len == window: decode 3x the capacity; logits stay equal
        # to the full teacher-forcing forward because the band only ever
        # needs the surviving slots.
        cfg = _cfg(attn_window=4)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        T = 12
        toks = jax.random.randint(jax.random.PRNGKey(3), (2, T), 0, 64)
        full, _ = transformer_ref_apply(params, toks, cfg)
        cache = init_decode_cache(cfg, 2, 4)     # ring capacity = window
        step = jax.jit(
            lambda c, t: transformer_decode_step(params, c, t, cfg))
        for t in range(T):
            lg, cache = step(cache, toks[:, t])
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t]),
                atol=2e-4, rtol=2e-4, err_msg=f"position {t}")
        assert int(cache["pos"]) == T

    def test_windowless_ring_wrap_detectable_via_pos(self):
        # decode_step past max_len without a window: the API contract is
        # that callers size max_len to the sequence; `pos` exceeding the
        # ring capacity is the observable signal of misuse.
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        cache = init_decode_cache(cfg, 1, 4)
        tok = jnp.zeros((1,), jnp.int32)
        for _ in range(5):
            _, cache = transformer_decode_step(params, cache, tok, cfg)
        assert int(cache["pos"]) == 5 > cache["k"].shape[2]

    def test_windowed_generate_with_small_ring(self):
        cfg = _cfg(attn_window=4)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 4), 0, 64)
        out, cache = transformer_generate(params, cfg, prompt, 10,
                                          max_len=4)
        assert out.shape == (1, 10) and int(cache["pos"]) == 14

    def test_ring_smaller_than_window(self):
        # A cache smaller than the window is legal as long as the ring
        # never wraps (r4 advisor): init accepts it, a NON-wrapping
        # generate works, and a WRAPPING generate is rejected eagerly.
        cfg = _cfg(attn_window=8)
        init_decode_cache(cfg, 1, 4)           # no raise
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 2), 0, 64)
        out, cache = transformer_generate(params, cfg, prompt, 2,
                                          max_len=4)
        assert out.shape == (1, 2) and int(cache["pos"]) == 4
        with pytest.raises(ValueError, match="wraps the ring"):
            transformer_generate(params, cfg, prompt, 6, max_len=4)

    def test_short_ring_matches_full_cache_when_not_wrapping(self):
        # Same tokens whether the cache is exactly-sized (< window) or
        # generously sized: a non-wrapping short ring changes nothing.
        cfg = _cfg(attn_window=8)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 3), 0, 64)
        out_short, _ = transformer_generate(params, cfg, prompt, 3,
                                            max_len=6)
        out_full, _ = transformer_generate(params, cfg, prompt, 3,
                                           max_len=32)
        assert (np.asarray(out_short) == np.asarray(out_full)).all()

    def test_prefill_requires_fresh_cache(self):
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 4), 0, 64)
        cache = init_decode_cache(cfg, 1, 16)
        _, warm = transformer_prefill(params, cache, prompt, cfg)
        with pytest.raises(ValueError, match="fresh cache"):
            transformer_prefill(params, warm, prompt, cfg)


class TestShardedDecode:
    """make_decode_step: KV-cache decode over a dp x tp mesh must equal
    single-device decode bit-for-near (distributed inference)."""

    def _mesh(self, **shape):
        from jax.sharding import Mesh

        n = 1
        for v in shape.values():
            n *= v
        if len(jax.devices()) < n:
            pytest.skip(f"needs {n} virtual devices")
        devs = np.array(jax.devices()[:n]).reshape(*shape.values())
        return Mesh(devs, tuple(shape.keys()))

    @pytest.mark.parametrize("shape,kw", [
        ({"dp": 2, "tp": 2}, {}),
        ({"tp": 2}, {"n_kv_heads": 2}),
        ({"dp": 2}, {"moe_every": 2, "n_experts": 2}),
        ({"tp": 2}, {"moe_every": 2, "n_experts": 2}),
    ], ids=["dp2tp2", "tp2-gqa", "dp2-moe", "tp2-moe"])
    def test_matches_single_device(self, shape, kw):
        from horovod_tpu.models import make_decode_step

        cfg = _cfg(**kw)
        mesh = self._mesh(**shape)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, 64)

        # single-device reference chain
        ref_cache = init_decode_cache(cfg, 2, 10)
        from horovod_tpu.models import transformer_prefill
        ref_lg, ref_cache = transformer_prefill(params, ref_cache,
                                                toks, cfg)

        step, prefill, shard_params, shard_cache, shard_tokens, _ = \
            make_decode_step(mesh, cfg)
        sp = shard_params(params)
        sc = shard_cache(init_decode_cache(cfg, 2, 10))
        lg, sc = prefill(sp, sc, toks)
        np.testing.assert_allclose(np.asarray(lg), np.asarray(ref_lg),
                                   atol=3e-4, rtol=3e-4)
        nxt = jnp.argmax(lg, axis=-1)
        for _ in range(3):
            ref_lg, ref_cache = transformer_decode_step(
                params, ref_cache, nxt, cfg)
            lg, sc = step(sp, sc, shard_tokens(nxt))
            np.testing.assert_allclose(np.asarray(lg),
                                       np.asarray(ref_lg),
                                       atol=3e-4, rtol=3e-4)
            nxt = jnp.argmax(lg, axis=-1)

    def test_sharded_extend_matches_single_device(self):
        # The speculative verify pass at dp2 x tp2: chunked extend over
        # the sharded cache equals the single-device chunk.
        from horovod_tpu.models import make_decode_step, transformer_extend

        cfg = _cfg(n_kv_heads=2)
        mesh = self._mesh(dp=2, tp=2)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, 64)
        chunk = jax.random.randint(jax.random.PRNGKey(2), (2, 3), 0, 64)

        ref_cache = init_decode_cache(cfg, 2, 10)
        _, ref_cache = transformer_prefill(params, ref_cache, toks, cfg)
        ref_lg, ref_cache = transformer_extend(params, ref_cache,
                                               chunk, cfg)

        bundle = make_decode_step(mesh, cfg)
        sp = bundle.shard_params(params)
        sc = bundle.shard_cache(init_decode_cache(cfg, 2, 10))
        _, sc = bundle.prefill(sp, sc, toks)
        lg, sc = bundle.extend(sp, sc, chunk)
        np.testing.assert_allclose(np.asarray(lg), np.asarray(ref_lg),
                                   atol=3e-4, rtol=3e-4)
        assert int(jax.device_get(sc["pos"])) == \
            int(ref_cache["pos"]) == 7

    def test_unsupported_axes_raise(self):
        from horovod_tpu.models import make_decode_step

        mesh = self._mesh(sp=2)
        with pytest.raises(NotImplementedError, match="dp/tp"):
            make_decode_step(mesh, _cfg())
        mesh = self._mesh(ep=2)
        with pytest.raises(NotImplementedError, match="ep"):
            make_decode_step(mesh, _cfg(moe_every=2, n_experts=2))


class TestTopP:
    def test_top_p_validation(self):
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((1, 2), jnp.int32)
        with pytest.raises(ValueError, match="top_p"):
            transformer_generate(params, cfg, prompt, 2, temperature=1.0,
                                 top_p=0.0, rng=jax.random.PRNGKey(0))

    def test_top_p_small_is_greedy(self):
        # top_p -> 0+ keeps only the argmax token, so sampling at any
        # temperature reproduces the greedy chain.
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 3), 0, 64)
        greedy, _ = transformer_generate(params, cfg, prompt, 5)
        nucleus, _ = transformer_generate(params, cfg, prompt, 5,
                                          temperature=2.0, top_p=1e-6,
                                          rng=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(nucleus),
                                      np.asarray(greedy))

    def test_eos_pads_tail(self):
        # Force a guaranteed eos hit: eos_id = the greedy chain's own
        # second token; everything strictly after its first occurrence
        # must read eos_id, positions up to and including it unchanged.
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 3), 0, 64)
        plain, _ = transformer_generate(params, cfg, prompt, 8)
        eos = int(plain[0, 1])
        stopped, _ = transformer_generate(params, cfg, prompt, 8,
                                          eos_id=eos)
        got = np.asarray(stopped[0])
        ref = np.asarray(plain[0])
        first = int(np.argmax(ref == eos))
        np.testing.assert_array_equal(got[: first + 1], ref[: first + 1])
        assert (got[first + 1:] == eos).all()

    def test_eos_absent_is_noop_and_validated(self):
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 3), 0, 64)
        plain, _ = transformer_generate(params, cfg, prompt, 6)
        # Pick an id the greedy chain never emits.
        unused = next(v for v in range(64)
                      if v not in np.asarray(plain).ravel())
        same, _ = transformer_generate(params, cfg, prompt, 6,
                                       eos_id=unused)
        np.testing.assert_array_equal(np.asarray(same),
                                      np.asarray(plain))
        with pytest.raises(ValueError, match="eos_id"):
            transformer_generate(params, cfg, prompt, 2, eos_id=999)

    def test_top_k_one_is_greedy(self):
        # top_k=1 keeps only the argmax token: sampling at any
        # temperature reproduces the greedy chain exactly.
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 3), 0, 64)
        greedy, _ = transformer_generate(params, cfg, prompt, 5)
        topk, _ = transformer_generate(params, cfg, prompt, 5,
                                       temperature=2.0, top_k=1,
                                       rng=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(topk),
                                      np.asarray(greedy))

    def test_top_k_validation(self):
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((1, 2), jnp.int32)
        with pytest.raises(ValueError, match="top_k"):
            transformer_generate(params, cfg, prompt, 2, temperature=1.0,
                                 top_k=-1, rng=jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="top_k"):
            transformer_generate(params, cfg, prompt, 2, temperature=1.0,
                                 top_k=10_000, rng=jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="temperature"):
            transformer_generate(params, cfg, prompt, 2, top_k=4)

    def test_top_k_tokens_stay_in_top_k(self):
        # Every sampled token must be within the top-k of the model's
        # own distribution at its position (teacher-forced check).
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 3), 0, 64)
        out, _ = transformer_generate(params, cfg, prompt, 8,
                                      temperature=3.0, top_k=2,
                                      rng=jax.random.PRNGKey(11))
        seq = jnp.concatenate([prompt, out], axis=1)
        logits, _ = transformer_ref_apply(params, seq, cfg)
        for i in range(8):
            pos = prompt.shape[1] - 1 + i
            top2 = np.argsort(-np.asarray(logits[0, pos]))[:2]
            assert int(out[0, i]) in top2, (i, int(out[0, i]), top2)

    def test_top_k_with_top_p_runs(self):
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((1, 2), jnp.int32)
        out, _ = transformer_generate(params, cfg, prompt, 4,
                                      temperature=1.0, top_p=0.9,
                                      top_k=8, rng=jax.random.PRNGKey(3))
        arr = np.asarray(out)
        assert arr.shape == (1, 4)
        assert ((arr >= 0) & (arr < 64)).all()

    def test_top_p_sampling_runs(self):
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((1, 2), jnp.int32)
        out, _ = transformer_generate(params, cfg, prompt, 4,
                                      temperature=1.0, top_p=0.9,
                                      rng=jax.random.PRNGKey(0))
        assert out.shape == (1, 4)
        assert bool((out >= 0).all()) and bool((out < 64).all())


class TestBeamSearch:
    def test_width_one_equals_greedy(self):
        from horovod_tpu.models import transformer_beam_search

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, 64)
        greedy, _ = transformer_generate(params, cfg, prompt, 6)
        beams, scores = transformer_beam_search(params, cfg, prompt, 6,
                                                beam_width=1)
        assert beams.shape == (2, 1, 6)
        np.testing.assert_array_equal(np.asarray(beams[:, 0]),
                                      np.asarray(greedy))

    def test_eos_freezes_beam_score_and_tail(self):
        # Pick eos = a token inside the plain best beam: with eos_id
        # set, that beam's tail after its first eos must read eos and
        # its score must equal the teacher-forced logprob sum up to and
        # INCLUDING the first eos (forced continuations add 0).
        from horovod_tpu.models import transformer_beam_search

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, 64)
        N, W = 6, 3
        plain, _ = transformer_beam_search(params, cfg, prompt, N,
                                           beam_width=W)
        eos = int(plain[0, 0, 2])
        beams, scores = transformer_beam_search(params, cfg, prompt, N,
                                                beam_width=W,
                                                eos_id=eos)
        arr = np.asarray(beams)
        # Non-vacuity: the chosen eos must actually appear somewhere.
        assert any(eos in arr[0, b] for b in range(W)), arr
        for b in range(W):
            row = arr[0, b]
            if eos in row:
                first = int(np.argmax(row == eos))
                assert (row[first:] == eos).all(), (b, row)
                # Teacher-forced score of the truncated chain.
                seq = jnp.concatenate(
                    [prompt, jnp.asarray(row[: first + 1])[None]],
                    axis=1)
                logits, _ = transformer_ref_apply(params, seq, cfg)
                lp = jax.nn.log_softmax(logits, axis=-1)
                picked = jnp.take_along_axis(
                    lp[:, 3:-1], seq[:, 4:, None].astype(jnp.int32),
                    -1)[..., 0]
                np.testing.assert_allclose(
                    float(scores[0, b]), float(picked.sum()),
                    rtol=2e-4, atol=2e-4)

    def test_eos_length_penalty_uses_actual_lengths(self):
        # Reported scores must equal the teacher-forced raw chain
        # logprob (to first eos) divided by the ACTUAL length —
        # a uniform max_new normalization fails this whenever any
        # beam finished early.
        from horovod_tpu.models import transformer_beam_search

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, 64)
        N, W = 6, 3
        plain, _ = transformer_beam_search(params, cfg, prompt, N,
                                           beam_width=W)
        eos = int(plain[0, 0, 2])
        beams, scores = transformer_beam_search(
            params, cfg, prompt, N, beam_width=W, eos_id=eos,
            length_penalty=1.0)
        arr = np.asarray(beams)
        lengths = []
        for b in range(W):
            row = arr[0, b]
            first = (int(np.argmax(row == eos)) if eos in row else N - 1)
            length = first + 1
            lengths.append(length)
            seq = jnp.concatenate(
                [prompt, jnp.asarray(row[: length])[None]], axis=1)
            logits, _ = transformer_ref_apply(params, seq, cfg)
            lp = jax.nn.log_softmax(logits, axis=-1)
            raw = float(jnp.take_along_axis(
                lp[:, 3:-1], seq[:, 4:, None].astype(jnp.int32),
                -1)[..., 0].sum())
            np.testing.assert_allclose(float(scores[0, b]),
                                       raw / length,
                                       rtol=3e-4, atol=3e-4)
        # Non-vacuity: at least one beam must have finished early.
        assert min(lengths) < N, lengths
        # Output stays sorted best-first after the re-sort.
        s = np.asarray(scores[0])
        assert (np.diff(s) <= 1e-6).all(), s
        with pytest.raises(ValueError, match="eos_id"):
            transformer_beam_search(params, cfg, prompt, 4,
                                    beam_width=2, eos_id=999)

    def test_scores_are_true_chain_logprobs(self):
        # Each returned beam's score must equal the sum of the chosen
        # tokens' logprobs under teacher forcing of that beam.
        from horovod_tpu.models import transformer_beam_search

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, 64)
        N = 5
        beams, scores = transformer_beam_search(params, cfg, prompt, N,
                                                beam_width=3)
        for w in range(3):
            seq = jnp.concatenate([prompt, beams[:, w]], axis=1)
            logits, _ = transformer_ref_apply(params, seq, cfg)
            lp = jax.nn.log_softmax(logits, axis=-1)
            picked = jnp.take_along_axis(
                lp[:, 3:-1], seq[:, 4:, None].astype(jnp.int32),
                axis=-1)[..., 0]
            want = float(picked.sum())
            assert abs(want - float(scores[0, w])) < 5e-3, (w, want,
                                                            scores)

    def test_best_beam_at_least_greedy(self):
        from horovod_tpu.models import transformer_beam_search

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 3), 0, 64)
        N = 6
        _, s1 = transformer_beam_search(params, cfg, prompt, N,
                                        beam_width=1)
        _, s4 = transformer_beam_search(params, cfg, prompt, N,
                                        beam_width=4)
        assert bool((s4[:, 0] >= s1[:, 0] - 1e-5).all())

    def test_width_validation(self):
        from horovod_tpu.models import transformer_beam_search

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((1, 2), jnp.int32)
        with pytest.raises(ValueError, match="beam_width"):
            transformer_beam_search(params, cfg, prompt, 2, beam_width=0)


class TestGenerateValidation:
    def test_top_p_without_temperature_rejected(self):
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((1, 2), jnp.int32)
        with pytest.raises(ValueError, match="temperature"):
            transformer_generate(params, cfg, prompt, 2, top_p=0.9)


class TestQuantizedCache:
    """int8 KV cache: ~1/4 the bytes, per-vector max-abs scales, decode
    logits within quantization noise of the full-precision path."""

    def test_cache_bytes_quartered(self):
        # Realistic head dim (64): scale overhead is 4/64 per element.
        cfg = _cfg(d_head=64, d_model=256)   # compute_dtype f32
        full = init_decode_cache(cfg, 2, 16)
        q8 = init_decode_cache(cfg, 2, 16, quantize="int8")
        full_bytes = full["k"].size * 4
        q8_bytes = q8["k"]["q"].size + q8["k"]["scale"].size * 4
        assert q8_bytes < full_bytes / 3.5

    def test_decode_close_to_full_precision(self):
        cfg = _cfg(n_kv_heads=2)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        cf = init_decode_cache(cfg, 2, 10)
        cq = init_decode_cache(cfg, 2, 10, quantize="int8")
        stepf = jax.jit(
            lambda c, t: transformer_decode_step(params, c, t, cfg))
        stepq = jax.jit(
            lambda c, t: transformer_decode_step(params, c, t, cfg))
        worst = 0.0
        for t in range(10):
            lf, cf = stepf(cf, toks[:, t])
            lq, cq = stepq(cq, toks[:, t])
            denom = float(jnp.max(jnp.abs(lf))) or 1.0
            worst = max(worst,
                        float(jnp.max(jnp.abs(lf - lq))) / denom)
        assert worst < 0.05, worst        # int8 noise, not divergence
        assert worst > 0.0                # and genuinely quantized

    def test_generate_and_beam_with_int8(self):
        from horovod_tpu.models import transformer_beam_search

        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, 64)
        out, cache = transformer_generate(params, cfg, prompt, 5,
                                          quantize="int8")
        assert out.shape == (1, 5)
        assert cache["k"]["q"].dtype == jnp.int8
        beams, scores = transformer_beam_search(
            params, cfg, prompt, 5, beam_width=2, quantize="int8")
        assert beams.shape == (1, 2, 5)

    def test_sharded_int8_matches_single_device(self):
        from jax.sharding import Mesh
        from horovod_tpu.models import make_decode_step

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 virtual devices")
        cfg = _cfg(n_kv_heads=2)
        mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, 64)
        ref_cache = init_decode_cache(cfg, 2, 8, quantize="int8")
        from horovod_tpu.models import transformer_prefill
        ref_lg, ref_cache = transformer_prefill(params, ref_cache,
                                                toks, cfg)
        step, prefill, shard_params, shard_cache, shard_tokens, _ = \
            make_decode_step(mesh, cfg, quantize="int8")
        sp = shard_params(params)
        sc = shard_cache(init_decode_cache(cfg, 2, 8, quantize="int8"))
        lg, sc = prefill(sp, sc, toks)
        np.testing.assert_allclose(np.asarray(lg), np.asarray(ref_lg),
                                   atol=3e-4, rtol=3e-4)
        nxt = jnp.argmax(lg, axis=-1)
        lg2, sc = step(sp, sc, shard_tokens(nxt))
        ref_lg2, ref_cache = transformer_decode_step(params, ref_cache,
                                                     nxt, cfg)
        np.testing.assert_allclose(np.asarray(lg2),
                                   np.asarray(ref_lg2),
                                   atol=3e-4, rtol=3e-4)

    def test_fp8_cache_close_to_full_precision(self):
        cfg = _cfg()
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 64)
        cf = init_decode_cache(cfg, 1, 8)
        cq = init_decode_cache(cfg, 1, 8, quantize="fp8_e4m3")
        assert cq["k"]["q"].dtype == jnp.float8_e4m3fn
        worst = 0.0
        for t in range(8):
            lf, cf = transformer_decode_step(params, cf, toks[:, t], cfg)
            lq, cq = transformer_decode_step(params, cq, toks[:, t], cfg)
            denom = float(jnp.max(jnp.abs(lf))) or 1.0
            worst = max(worst,
                        float(jnp.max(jnp.abs(lf - lq))) / denom)
        assert 0.0 < worst < 0.08, worst   # e4m3 ~2 mantissa bits

    def test_bad_quantize_rejected(self):
        with pytest.raises(ValueError, match="quantize"):
            init_decode_cache(_cfg(), 1, 8, quantize="fp4")


def test_sharded_fp8_cache_builds_and_steps():
    from jax.sharding import Mesh
    from horovod_tpu.models import make_decode_step

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    cfg = _cfg(n_kv_heads=2)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, 64)
    step, prefill, shard_params, shard_cache, shard_tokens, _ = \
        make_decode_step(mesh, cfg, quantize="fp8_e4m3")
    sp = shard_params(params)
    sc = shard_cache(init_decode_cache(cfg, 2, 6, quantize="fp8_e4m3"))
    lg, sc = prefill(sp, sc, toks)
    lg, sc = step(sp, sc, shard_tokens(jnp.argmax(lg, axis=-1)))
    assert bool(jnp.isfinite(lg).all())
