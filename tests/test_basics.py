"""Init/rank/size/process-set tests (reference: basics exposed via
horovod/common/basics.py; process sets via horovod/common/process_sets.py).
"""

import jax
import pytest

import horovod_tpu as hvd
from horovod_tpu.common.exceptions import HorovodTpuError


def test_sizes():
    assert hvd.is_initialized()
    assert hvd.size() == 8
    assert hvd.local_size() == 8
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.is_homogeneous()
    assert hvd.local_device_ranks() == list(range(8))


def test_double_init_is_noop():
    hvd.init()
    assert hvd.size() == 8


def test_build_info():
    assert hvd.xla_built()
    assert hvd.gloo_built()
    assert not hvd.mpi_built()
    assert not hvd.nccl_built()
    assert not hvd.ccl_built()
    assert not hvd.mpi_threads_supported()


def test_global_process_set():
    ps = hvd.global_process_set()
    assert ps.process_set_id == 0
    assert ps.ranks == list(range(8))
    assert ps.size() == 8
    assert ps.included()
    assert ps.rank() == 0


def test_add_remove_process_set():
    ps = hvd.add_process_set([0, 2, 4, 6])
    assert ps.process_set_id > 0
    assert ps.size() == 4
    assert ps.mesh is not None
    with pytest.raises(HorovodTpuError):
        hvd.add_process_set([0, 2, 4, 6])  # duplicate
    hvd.remove_process_set(ps)
    with pytest.raises(HorovodTpuError):
        hvd.get_process_set(ps.process_set_id)


def test_cannot_remove_global_set():
    with pytest.raises(HorovodTpuError):
        hvd.remove_process_set(hvd.global_process_set())


def test_out_of_range_process_set():
    with pytest.raises(HorovodTpuError):
        hvd.add_process_set([0, 99])


def test_duplicate_ranks_in_process_set_rejected():
    # A repeated rank would silently shrink the set after dedup (and
    # downstream axis_index_groups must cover the axis exactly once) —
    # reject loudly at registration instead.
    with pytest.raises(HorovodTpuError, match="duplicate"):
        hvd.add_process_set([0, 2, 2, 4])
