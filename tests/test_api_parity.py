"""Upstream public-API inventory checks.

One test per frontend namespace asserting the canonical upstream
Horovod surface (SURVEY.md §2.4: horovod/{tensorflow,torch,mxnet}/
__init__.py + mpi_ops.py, horovod/tensorflow/keras, horovod/common/
basics.py) exists here under the same names.  This is the
completeness tripwire: removing or renaming any reference-parity
symbol fails loudly.
"""

import importlib

import pytest

BASICS = [
    "init", "shutdown", "is_initialized", "size", "rank",
    "local_size", "local_rank", "cross_size", "cross_rank",
    "mpi_threads_supported", "mpi_enabled", "gloo_enabled",
    "mpi_built", "gloo_built", "nccl_built", "ddl_built", "ccl_built",
    "cuda_built", "rocm_built",
    "ProcessSet", "add_process_set", "remove_process_set",
]

OPS_COMMON = [
    "allreduce", "allgather", "broadcast", "alltoall", "reducescatter",
    "grouped_allreduce", "barrier", "join",
    "Average", "Sum", "Adasum", "Min", "Max", "Product", "Compression",
]

SURFACES = {
    "horovod_tpu": BASICS + OPS_COMMON + [
        # jax-native frontend: reference hvd.* core plus tape/optimizer
        "allreduce_async", "allgather_async", "broadcast_async",
        "grouped_allreduce_async", "grouped_allgather",
        "grouped_reducescatter", "poll", "synchronize",
        "broadcast_parameters", "broadcast_optimizer_state",
        "broadcast_object", "allgather_object",
        "DistributedOptimizer", "DistributedGradientTape", "elastic",
        "start_timeline", "stop_timeline",
    ],
    "horovod_tpu.tensorflow": BASICS + OPS_COMMON + [
        "allreduce_async", "allgather_async", "broadcast_async",
        "grouped_allgather", "grouped_reducescatter",
        "DistributedOptimizer", "DistributedGradientTape",
        "broadcast_variables", "broadcast_global_variables",
        "broadcast_object", "broadcast_object_fn", "allgather_object",
        "SyncBatchNormalization", "elastic",
        "rank_op", "local_rank_op", "size_op", "local_size_op",
        "process_set_included_op", "poll", "synchronize",
    ],
    "horovod_tpu.tensorflow.keras": [
        "init", "shutdown", "size", "rank", "local_size", "local_rank",
        "allreduce", "allgather", "broadcast", "broadcast_object",
        "DistributedOptimizer", "PartialDistributedOptimizer",
        "load_model", "callbacks", "elastic",
        "Average", "Sum", "Adasum", "Compression",
        "mpi_built", "gloo_built", "nccl_built",
    ],
    "horovod_tpu.keras": [
        "init", "size", "rank", "DistributedOptimizer",
        "PartialDistributedOptimizer", "load_model",
        "callbacks", "elastic", "Compression",
    ],
    "horovod_tpu.torch": BASICS + OPS_COMMON + [
        "allreduce_", "allreduce_async", "allreduce_async_",
        "allgather_async", "allgather_object",
        "broadcast_", "broadcast_async", "broadcast_async_",
        "alltoall_async", "reducescatter_async",
        "grouped_allreduce_async", "grouped_allreduce_async_",
        "grouped_allgather", "grouped_allgather_async",
        "grouped_reducescatter", "sparse_allreduce_async",
        "poll", "synchronize",
        "DistributedOptimizer", "broadcast_parameters",
        "broadcast_optimizer_state", "broadcast_object",
        "SyncBatchNorm", "elastic",
    ],
    "horovod_tpu.mxnet": BASICS + OPS_COMMON + [
        "allreduce_", "broadcast_", "grouped_allreduce_",
        "grouped_allgather", "grouped_reducescatter",
        "DistributedOptimizer", "DistributedTrainer",
        "broadcast_parameters", "broadcast_object",
    ],
}

CALLBACKS = [
    "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
    "LearningRateWarmupCallback", "LearningRateScheduleCallback",
]


@pytest.mark.parametrize("modname", sorted(SURFACES))
def test_surface_complete(modname):
    mod = importlib.import_module(modname)
    missing = [s for s in SURFACES[modname] if not hasattr(mod, s)]
    assert not missing, f"{modname} missing upstream symbols: {missing}"


@pytest.mark.parametrize(
    "modname",
    ["horovod_tpu.tensorflow.keras.callbacks", "horovod_tpu.keras.callbacks"])
def test_keras_callbacks_complete(modname):
    mod = importlib.import_module(modname)
    missing = [s for s in CALLBACKS if not hasattr(mod, s)]
    assert not missing, f"{modname} missing callbacks: {missing}"


def test_elastic_surface():
    import horovod_tpu.elastic as el

    for s in ["run", "State", "ObjectState"]:
        assert hasattr(el, s), s
    import horovod_tpu.torch.elastic as tel

    assert hasattr(tel, "TorchState")
    import horovod_tpu.tensorflow.elastic as tfel

    assert hasattr(tfel, "TensorFlowKerasState")
    import horovod_tpu.tensorflow.keras.elastic as kel

    for s in ["KerasState", "CommitStateCallback",
              "UpdateBatchStateCallback", "UpdateEpochStateCallback"]:
        assert hasattr(kel, s), s


def test_runner_surface():
    from horovod_tpu.runner import api

    assert hasattr(api, "run")
    import horovod_tpu.spark as spark

    for s in ["run", "run_elastic"]:
        assert hasattr(spark, s), s


def test_ray_surface():
    # Reference: horovod/ray/__init__.py exports (SURVEY §2.5 Ray row).
    import horovod_tpu.ray as ray_mod

    for s in ["RayExecutor", "ElasticRayExecutor", "RayHostDiscovery",
              "RayTransport", "assign_ranks", "ray_available"]:
        assert hasattr(ray_mod, s), s
