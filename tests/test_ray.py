"""Ray integration tests against an injected fake ray module
(reference: test/single/test_ray.py + test_ray_elastic.py's fake local
cluster — SURVEY §4).  The REAL `horovod_tpu.ray` code paths run:
actor-pool start/run/failure, cluster discovery, and the full elastic
driver with Ray discovery + Ray-actor worker spawn (workers are real
subprocesses; only the ray API is faked).
"""

import os
import sys
import time
import threading

import pytest

import horovod_tpu.ray as hvd_ray
from fake_ray import FakeRay
from horovod_tpu.ray import (
    ElasticRayExecutor,
    RayExecutor,
    RayHostDiscovery,
    RayTransport,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def fake_ray(monkeypatch):
    fake = FakeRay()
    monkeypatch.setattr(hvd_ray, "_ray", fake)
    return fake


def fn_const():
    return 42


def fn_read_env():
    return os.environ.get("HOROVOD_RANK")


def fn_boom():
    raise RuntimeError("boom from actor")


class TestRayExecutorActors:
    def test_start_assigns_ranks_and_runs(self, fake_ray):
        ex = RayExecutor(num_workers=3)
        ex.start()
        assert len(fake_ray.actors) == 3
        # Orchestration: each actor received its rank env exactly once,
        # with a shared coordinator address.
        set_envs = [c for c in fake_ray.calls if c[1] == "set_env"]
        assert len(set_envs) == 3
        ranks = sorted(int(c[2][0]["HOROVOD_RANK"]) for c in set_envs)
        assert ranks == [0, 1, 2]
        coords = {c[2][0]["HOROVOD_COORDINATOR_ADDR"] for c in set_envs}
        assert len(coords) == 1
        sizes = {int(c[2][0]["HOROVOD_SIZE"]) for c in set_envs}
        assert sizes == {3}
        assert ex.run(fn_const) == [42, 42, 42]
        ex.shutdown()
        assert all(not a._alive for a in fake_ray.actors)

    def test_failure_propagates(self, fake_ray):
        ex = RayExecutor(num_workers=2)
        ex.start()
        with pytest.raises(RuntimeError, match="boom from actor"):
            ex.run(fn_boom)
        # Pool survives a failed call (reference: actors outlive task
        # exceptions).
        assert ex.run(fn_const) == [42, 42]
        ex.shutdown()

    def test_run_remote_then_get(self, fake_ray):
        ex = RayExecutor(num_workers=2)
        ex.start()
        tokens = ex.run_remote(fn_const)
        assert ex.get(tokens) == [42, 42]
        ex.shutdown()

    def test_not_started_raises(self, fake_ray):
        from horovod_tpu.common.exceptions import HorovodTpuError

        with pytest.raises(HorovodTpuError, match="not started"):
            RayExecutor(num_workers=2).run(fn_const)

    def test_use_gpu_rejected(self, fake_ray):
        from horovod_tpu.common.exceptions import HorovodTpuError

        with pytest.raises(HorovodTpuError, match="use_gpu"):
            RayExecutor(num_workers=1, use_gpu=True)


class TestRayHostDiscovery:
    def test_nodes_to_slots(self, fake_ray):
        fake_ray.set_nodes([
            {"Alive": True, "NodeManagerHostname": "a",
             "Resources": {"CPU": 4}},
            {"Alive": True, "NodeManagerHostname": "b",
             "Resources": {"CPU": 2}},
            {"Alive": False, "NodeManagerHostname": "dead",
             "Resources": {"CPU": 8}},
        ])
        d = RayHostDiscovery(fake_ray)
        assert d.find_available_hosts_and_slots() == {"a": 4, "b": 2}

    def test_cpus_per_slot_and_min(self, fake_ray):
        fake_ray.set_nodes([
            {"Alive": True, "NodeManagerHostname": "a",
             "Resources": {"CPU": 5}},
            {"Alive": True, "NodeManagerHostname": "tiny",
             "Resources": {}},
        ])
        d = RayHostDiscovery(fake_ray, cpus_per_slot=2)
        assert d.find_available_hosts_and_slots() == {"a": 2, "tiny": 1}

    def test_advertised_small_cpu_gets_zero_slots(self, fake_ray):
        # min_slots is a floor for nodes that advertise NO CPU resource
        # at all; a node that advertises a small or fractional CPU count
        # is telling us its true capacity and must NOT be rounded up —
        # 1 // 2 == 0 slots, and get_host_assignments simply skips
        # 0-slot hosts.
        fake_ray.set_nodes([
            {"Alive": True, "NodeManagerHostname": "small",
             "Resources": {"CPU": 1}},
            {"Alive": True, "NodeManagerHostname": "frac",
             "Resources": {"CPU": 0.5}},
            {"Alive": True, "NodeManagerHostname": "bare",
             "Resources": {}},
        ])
        d = RayHostDiscovery(fake_ray, cpus_per_slot=2)
        assert d.find_available_hosts_and_slots() == \
            {"small": 0, "frac": 0, "bare": 1}


def fn_elastic_size():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()
    # Default elastic mode is single-controller JAX per worker; job
    # membership lives in the env the driver/generation protocol
    # maintains (same convention as tests/data/elastic_main.py).
    return int(os.environ["HOROVOD_SIZE"])


def fn_elastic_epochs():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()
    state = hvd.elastic.ObjectState(epoch=0)

    @hvd.elastic.run
    def train(state):
        num_epochs = int(os.environ.get("NUM_EPOCHS", "6"))
        marker = os.environ.get("FAIL_MARKER")
        while state.epoch < num_epochs:
            if marker and os.path.exists(marker):
                with open(marker) as f:
                    if f.read().strip() == os.environ.get(
                            "HOROVOD_HOSTNAME"):
                        sys.exit(1)
            time.sleep(float(os.environ.get("EPOCH_TIME", "0.4")))
            state.epoch += 1
            state.commit()
        return int(os.environ["HOROVOD_SIZE"])

    return train(state)


@pytest.mark.integration
class TestElasticRayNative:
    """The REAL elastic driver loop with Ray discovery + Ray transport:
    workers are genuine subprocesses spawned via the per-host agent
    actor, results return through the rendezvous KV."""

    def _clean(self, monkeypatch):
        monkeypatch.delenv("XLA_FLAGS", raising=False)
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")

    def test_static_run(self, fake_ray, monkeypatch):
        self._clean(monkeypatch)
        ex = ElasticRayExecutor(min_np=2, cpus_per_slot=1)
        results = ex.run(fn_elastic_size)
        assert results == [2, 2]
        # Workers went through the agent actor, not local fork: the
        # fake recorded spawn calls.
        spawns = [c for c in fake_ray.calls if c[1] == "spawn"]
        assert len(spawns) == 2

    def test_rescale_up_mid_run(self, fake_ray, monkeypatch):
        self._clean(monkeypatch)
        monkeypatch.setenv("NUM_EPOCHS", "8")
        monkeypatch.setenv("EPOCH_TIME", "0.4")
        node = {"Alive": True, "NodeManagerHostname": "127.0.0.1",
                "NodeManagerAddress": "127.0.0.1",
                "Resources": {"CPU": 1}}
        fake_ray.set_nodes([node])

        def grow():
            time.sleep(2.0)
            fake_ray.set_nodes([dict(node, Resources={"CPU": 2})])

        t = threading.Thread(target=grow, daemon=True)
        t.start()
        ex = ElasticRayExecutor(min_np=1, cpus_per_slot=1)
        results = ex.run(fn_elastic_epochs)
        t.join()
        # Both final-generation workers finished at size 2.
        assert sorted(results) == [2, 2]

    def test_worker_failure_blacklists_host(self, fake_ray, monkeypatch,
                                            tmp_path):
        self._clean(monkeypatch)
        monkeypatch.setenv("NUM_EPOCHS", "6")
        monkeypatch.setenv("EPOCH_TIME", "0.4")
        monkeypatch.setenv("HVD_TPU_FAKE_LOCAL_HOSTS", "hostA,hostB")
        marker = tmp_path / "fail_marker"
        fake_ray.set_nodes([
            {"Alive": True, "NodeManagerHostname": h,
             "Resources": {"CPU": 1}}
            for h in ("hostA", "hostB")
        ])

        def fail_b():
            time.sleep(1.5)
            marker.write_text("hostB")

        t = threading.Thread(target=fail_b, daemon=True)
        t.start()
        ex = ElasticRayExecutor(
            min_np=1, cpus_per_slot=1,
            extra_env={"FAIL_MARKER": str(marker)})
        results = ex.run(fn_elastic_epochs)
        t.join()
        # hostB died and was blacklisted; the hostA survivor finished
        # alone at size 1.
        assert results == [1]

    def test_ray_transport_terminates_removed_workers(self, fake_ray):
        # Unit-level: handles route termination through their agent.
        tr = RayTransport(fake_ray)
        h = tr.execute([sys.executable, "-c", "import time; time.sleep(60)"],
                       env={"HOROVOD_HOSTNAME": "127.0.0.1",
                            "PATH": os.environ.get("PATH", "")},
                       prefix="t")
        assert h.poll() is None
        tr.terminate([h])
        deadline = time.time() + 10
        while h.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        assert h.poll() is not None
        tr.shutdown()
