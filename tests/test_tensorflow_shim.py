"""TF2/Keras frontend shim tests (reference: test/parallel/
test_tensorflow.py + test_tensorflow2_keras.py core assertions, adapted
to the one-process 8-rank sim).

On the 8-rank CPU mesh a plain tensor means "every rank contributes this
value", so Average round-trips values exactly; Sum scales by size —
mirroring the reference's self-consistency checks plus gradient-tape /
optimizer / broadcast / callback mechanics.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.tensorflow as hvd_tf  # noqa: E402
import horovod_tpu.tensorflow.keras as hvd_keras  # noqa: E402


class TestTfCollectiveGradients:
    """Reference: the RegisterGradient entries in
    horovod/tensorflow/__init__.py — tapes differentiate THROUGH
    collectives ('grad of allreduce' tests in test_tensorflow.py)."""

    def test_allreduce_gradient(self):
        import tensorflow as tf

        x = tf.Variable(tf.ones((4,)))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd_tf.allreduce(x * 2.0))
        g = tape.gradient(y, x)
        np.testing.assert_allclose(g.numpy(), np.full((4,), 2.0))

    def test_allgather_gradient(self):
        import tensorflow as tf

        x = tf.Variable(tf.ones((2, 3)))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd_tf.allgather(x))
        g = tape.gradient(y, x)
        np.testing.assert_allclose(
            g.numpy(), np.full((2, 3), float(hvd_tf.size())))

    def test_broadcast_gradient_root(self):
        import tensorflow as tf

        x = tf.Variable(tf.ones((3,)))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd_tf.broadcast(x, root_rank=0))
        g = tape.gradient(y, x)
        np.testing.assert_allclose(
            g.numpy(), np.full((3,), float(hvd_tf.size())))

    def test_reducescatter_gradient_average(self):
        import tensorflow as tf

        n = hvd_tf.size()
        x = tf.Variable(tf.ones((2 * n, 3)))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd_tf.reducescatter(x))
        g = tape.gradient(y, x)
        np.testing.assert_allclose(
            g.numpy(), np.full((2 * n, 3), 1.0 / n))

    def test_alltoall_gradient(self):
        import tensorflow as tf

        n = hvd_tf.size()
        x = tf.Variable(tf.ones((n, 2)))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd_tf.alltoall(x) * 3.0)
        g = tape.gradient(y, x)
        np.testing.assert_allclose(g.numpy(), np.full((n, 2), 3.0))


class TestTfOps:
    def test_allreduce_average_roundtrip(self):
        t = tf.constant([[1.0, 2.0], [3.0, 4.0]])
        out = hvd_tf.allreduce(t)
        assert isinstance(out, tf.Tensor)
        np.testing.assert_allclose(out.numpy(), t.numpy())

    def test_allreduce_sum_scales_by_size(self):
        t = tf.ones([5], dtype=tf.float32)
        out = hvd_tf.allreduce(t, op=hvd_tf.Sum)
        np.testing.assert_allclose(out.numpy(), 8.0 * np.ones(5))

    def test_allreduce_int_dtype(self):
        t = tf.constant([1, 2, 3], dtype=tf.int32)
        out = hvd_tf.allreduce(t, op=hvd_tf.Sum)
        assert out.dtype == tf.int32
        np.testing.assert_array_equal(out.numpy(), np.array([8, 16, 24]))

    def test_allreduce_fp16_compression(self):
        t = tf.constant([0.5, 1.5, 2.5])
        out = hvd_tf.allreduce(t, compression=hvd_tf.Compression.fp16)
        assert out.dtype == tf.float32
        np.testing.assert_allclose(out.numpy(), t.numpy(), rtol=1e-3)

    def test_allreduce_inside_tf_function(self):
        @tf.function
        def fn(x):
            return hvd_tf.allreduce(x, op=hvd_tf.Sum)

        out = fn(tf.ones([3]))
        np.testing.assert_allclose(out.numpy(), 8.0 * np.ones(3))

    def test_grouped_allreduce(self):
        ts = [tf.ones([2]), tf.constant([2.0, 4.0, 6.0])]
        outs = hvd_tf.grouped_allreduce(ts)
        assert len(outs) == 2
        np.testing.assert_allclose(outs[0].numpy(), np.ones(2))
        np.testing.assert_allclose(outs[1].numpy(), [2.0, 4.0, 6.0])

    def test_graph_mode_op_variants(self):
        assert int(hvd_tf.size_op().numpy()) == hvd_tf.size()
        assert int(hvd_tf.rank_op().numpy()) == hvd_tf.rank()
        assert int(hvd_tf.local_size_op().numpy()) == hvd_tf.local_size()
        ps = hvd_tf.add_process_set([0, 1])
        try:
            assert int(hvd_tf.size_op(ps).numpy()) == 2
            included = int(hvd_tf.process_set_included_op(ps).numpy())
            assert included == int(hvd_tf.rank() in (0, 1))
        finally:
            hvd_tf.remove_process_set(ps)

    def test_grouped_allgather(self):
        ts = [tf.ones([2, 3]), tf.zeros([1, 3])]
        outs = hvd_tf.grouped_allgather(ts)
        assert [int(o.shape[0]) for o in outs] == [
            2 * hvd_tf.size(), 1 * hvd_tf.size()]

    def test_grouped_reducescatter(self):
        n = hvd_tf.size()
        ts = [tf.ones([2 * n, 2]), tf.ones([n])]
        outs = hvd_tf.grouped_reducescatter(ts)
        assert tuple(outs[0].shape) == (2, 2)
        assert tuple(outs[1].shape) == (1,)
        np.testing.assert_allclose(outs[0].numpy(), np.ones((2, 2)))

    def test_allgather_concatenates(self):
        t = tf.reshape(tf.range(6, dtype=tf.float32), (2, 3))
        out = hvd_tf.allgather(t)
        assert out.shape == (16, 3)
        np.testing.assert_allclose(out.numpy()[:2], t.numpy())

    def test_broadcast(self):
        t = tf.constant([7.0, 8.0])
        out = hvd_tf.broadcast(t, root_rank=0)
        np.testing.assert_allclose(out.numpy(), t.numpy())

    def test_broadcast_variables_assigns(self):
        v = tf.Variable([1.0, 2.0, 3.0])
        hvd_tf.broadcast_variables([v], root_rank=0)
        np.testing.assert_allclose(v.numpy(), [1.0, 2.0, 3.0])

    def test_indexed_slices_sparse_allreduce(self):
        # Reference semantics: allreduce of IndexedSlices is the
        # allgather-based sparse path — IndexedSlices out, scatter-add
        # equal to the dense allreduce of the scattered input.
        values = tf.constant([[1.0, 1.0], [2.0, 2.0]])
        indices = tf.constant([0, 2], dtype=tf.int64)
        slices = tf.IndexedSlices(values, indices,
                                  dense_shape=tf.constant([4, 2],
                                                          dtype=tf.int64))
        out = hvd_tf.allreduce(slices, op=hvd_tf.Sum)
        assert isinstance(out, tf.IndexedSlices)
        assert int(out.values.shape[0]) == 2 * hvd_tf.size()
        dense_want = 8.0 * tf.convert_to_tensor(slices).numpy()
        dense_got = tf.scatter_nd(
            tf.expand_dims(out.indices, 1), out.values, [4, 2]).numpy()
        np.testing.assert_allclose(dense_got, dense_want)

    def test_indexed_slices_sparse_average_matches_dense(self):
        values = tf.constant([[3.0], [5.0]])
        indices = tf.constant([1, 3], dtype=tf.int64)
        slices = tf.IndexedSlices(values, indices,
                                  dense_shape=tf.constant([4, 1],
                                                          dtype=tf.int64))
        out = hvd_tf.allreduce(slices)  # Average
        dense_got = tf.scatter_nd(
            tf.expand_dims(out.indices, 1), out.values, [4, 1]).numpy()
        np.testing.assert_allclose(
            dense_got, tf.convert_to_tensor(slices).numpy())

    def test_fused_flat_allreduce_matches_per_tensor(self):
        # The TF-side fusion buffer (one flat bridge crossing per dtype)
        # must be numerically identical to per-tensor reduction.
        from horovod_tpu.tensorflow import _fused_flat_allreduce

        ts = [tf.constant([[1.0, 2.0], [3.0, 4.0]]),
              tf.constant([5.0, 6.0, 7.0]),
              tf.constant([1, 2, 3], dtype=tf.int32),
              tf.constant(9.0)]
        fused = _fused_flat_allreduce(
            ts, hvd_tf.Sum, hvd_tf.Compression.none, None)
        single = [hvd_tf.allreduce(t, op=hvd_tf.Sum) for t in ts]
        for f, s, t in zip(fused, single, ts):
            assert f.dtype == t.dtype and f.shape == t.shape
            np.testing.assert_allclose(np.asarray(f), np.asarray(s))

    def test_allreduce_grads_size1_process_set_short_circuits(self):
        # n==1 allreduce is the identity (reference np=1 = memcpy):
        # result returns unchanged without crossing the bridge.
        from horovod_tpu.tensorflow import _allreduce_grads

        ps = hvd_tf.add_process_set([hvd_tf.rank()])
        try:
            g = tf.constant([1.0, 2.0])
            out = _allreduce_grads([g, None], hvd_tf.Average,
                                   hvd_tf.Compression.none, ps,
                                   sparse_as_dense=False)
            assert out[0] is g and out[1] is None
        finally:
            hvd_tf.remove_process_set(ps)

    def test_allreduce_grads_sparse_vs_dense_switch(self):
        # Ragged embedding-style grads: sparse path result must equal
        # the sparse_as_dense=True densified path after scatter-add.
        from horovod_tpu.tensorflow import _allreduce_grads

        values = tf.constant([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        indices = tf.constant([0, 2, 2], dtype=tf.int64)
        mk = lambda: tf.IndexedSlices(  # noqa: E731
            values, indices,
            dense_shape=tf.constant([5, 2], dtype=tf.int64))
        dense_grad = tf.ones([3, 3])

        out_sparse = _allreduce_grads(
            [mk(), dense_grad, None], hvd_tf.Average,
            hvd_tf.Compression.none, None, sparse_as_dense=False)
        out_dense = _allreduce_grads(
            [mk(), dense_grad, None], hvd_tf.Average,
            hvd_tf.Compression.none, None, sparse_as_dense=True)

        assert isinstance(out_sparse[0], tf.IndexedSlices)
        assert not isinstance(out_dense[0], tf.IndexedSlices)
        scattered = tf.scatter_nd(
            tf.expand_dims(out_sparse[0].indices, 1),
            out_sparse[0].values, [5, 2]).numpy()
        np.testing.assert_allclose(scattered, out_dense[0].numpy(),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(out_sparse[1].numpy(),
                                   out_dense[1].numpy())
        assert out_sparse[2] is None and out_dense[2] is None

    def test_async_handle(self):
        h = hvd_tf.allreduce_async(tf.ones([4]), op=hvd_tf.Sum)
        assert hvd_tf.poll(h)
        out = hvd_tf.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), 8.0 * np.ones(4))

    def test_alltoall_even_splits(self):
        t = tf.ones([8, 2], dtype=tf.float32)
        out = hvd_tf.alltoall(t)
        assert out.shape[0] == 8


class TestDistributedGradientTape:
    def test_gradient_averaged(self):
        # Reference: test_tensorflow2_keras gradient-aggregation assert —
        # with identical contributions the averaged grad equals the local.
        x = tf.Variable(2.0)
        with tf.GradientTape() as tape:
            loss = x * x
        tape = hvd_tf.DistributedGradientTape(tape)
        (grad,) = tape.gradient(loss, [x])
        np.testing.assert_allclose(grad.numpy(), 4.0)

    def test_gradient_none_passthrough(self):
        x = tf.Variable(1.0)
        unused = tf.Variable(5.0)
        with tf.GradientTape() as tape:
            loss = 3.0 * x
        tape = hvd_tf.DistributedGradientTape(tape)
        grads = tape.gradient(loss, [x, unused])
        np.testing.assert_allclose(grads[0].numpy(), 3.0)
        assert grads[1] is None

    def test_tape_delegation(self):
        x = tf.Variable(3.0)
        with hvd_tf.DistributedGradientTape(
                tf.GradientTape(persistent=True)) as tape:
            y = x * x
            z = 2.0 * x
        (g1,) = tape.gradient(y, [x])
        (g2,) = tape.gradient(z, [x])
        np.testing.assert_allclose(g1.numpy(), 6.0)
        np.testing.assert_allclose(g2.numpy(), 2.0)


def _tiny_model():
    return tf.keras.Sequential([
        tf.keras.layers.Input(shape=(4,)),
        tf.keras.layers.Dense(8, activation="relu"),
        tf.keras.layers.Dense(2),
    ])


class TestKerasOptimizer:
    def test_distributed_optimizer_is_optimizer_subclass(self):
        base = tf.keras.optimizers.SGD(learning_rate=0.01)
        opt = hvd_keras.DistributedOptimizer(base)
        assert isinstance(opt, tf.keras.optimizers.SGD)
        assert float(opt.learning_rate.numpy()) == pytest.approx(0.01)

    def test_apply_gradients_updates(self):
        v = tf.Variable([1.0, 1.0])
        opt = hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.5))
        opt.apply_gradients([(tf.constant([2.0, 2.0]), v)])
        np.testing.assert_allclose(v.numpy(), [0.0, 0.0])

    def test_backward_passes_per_step_accumulates(self, monkeypatch):
        import horovod_tpu.tensorflow.keras as K

        calls = []
        orig = K._allreduce_grads

        def spy(*a, **kw):
            calls.append(1)
            return orig(*a, **kw)

        monkeypatch.setattr(K, "_allreduce_grads", spy)
        tf.keras.utils.set_random_seed(0)
        m = tf.keras.Sequential([tf.keras.layers.Input((2,)),
                                 tf.keras.layers.Dense(1)])
        opt = hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.1), backward_passes_per_step=2)
        m.compile(optimizer=opt, loss="mse")
        x = np.random.randn(8, 2).astype("float32")
        y = np.random.randn(8).astype("float32")
        w0 = m.get_weights()[0].copy()
        m.train_on_batch(x, y)      # accumulate only
        w1 = m.get_weights()[0].copy()
        m.train_on_batch(x, y)      # sync + apply
        w2 = m.get_weights()[0].copy()
        m.train_on_batch(x, y)
        m.train_on_batch(x, y)
        assert len(calls) == 2      # one allreduce per 2 batches
        np.testing.assert_array_equal(w0, w1)
        assert not np.allclose(w1, w2)

    def test_backward_passes_graph_mode_is_documented_exclusion(self):
        # TF1/graph-mode local aggregation is excluded by decision
        # (docs/MIGRATION.md); the boundary must be loud, not a numpy
        # conversion failure deep in the accumulate path.
        v = tf.Variable([1.0])
        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.5), backward_passes_per_step=2)

        @tf.function
        def step():
            opt.apply_gradients([(tf.constant([1.0]), v)])

        with pytest.raises(Exception, match="eager"):
            step()

    def test_model_fit_trains(self):
        # Reference: test_tensorflow2_keras train_model assertion — one
        # fit epoch under the wrapped optimizer reduces the loss.
        tf.keras.utils.set_random_seed(0)
        model = _tiny_model()
        opt = hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.1))
        model.compile(optimizer=opt,
                      loss=tf.keras.losses.SparseCategoricalCrossentropy(
                          from_logits=True))
        x = np.random.RandomState(0).randn(64, 4).astype(np.float32)
        y = (x.sum(axis=1) > 0).astype(np.int32)
        h = model.fit(x, y, epochs=3, batch_size=16, verbose=0)
        assert h.history["loss"][-1] < h.history["loss"][0]

    def test_load_model_wraps_optimizer(self, tmp_path):
        # Reference: horovod/tensorflow/keras load_model — a model saved
        # with a PLAIN optimizer deserializes with it Distributed-wrapped.
        model = _tiny_model()
        model.compile(optimizer=tf.keras.optimizers.Adam(1e-3), loss="mse")
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
        model.train_on_batch(x, y)   # build slot state before saving
        path = str(tmp_path / "m.keras")
        model.save(path)
        loaded = hvd_keras.load_model(path)
        assert isinstance(loaded.optimizer, tf.keras.optimizers.Adam)
        assert hasattr(loaded.optimizer, "_hvd_op")
        # Restored slot state must survive the wrap (iterations == 1).
        assert int(loaded.optimizer.iterations.numpy()) == 1
        loaded.train_on_batch(x, y)

    def test_load_model_custom_objects_opt_out(self, tmp_path):
        # Upstream merge precedence: an explicit custom_objects entry
        # for the optimizer class loads it UNWRAPPED.
        model = _tiny_model()
        model.compile(optimizer=tf.keras.optimizers.Adam(1e-3), loss="mse")
        path = str(tmp_path / "m.keras")
        model.save(path)
        loaded = hvd_keras.load_model(
            path, custom_objects={"Adam": tf.keras.optimizers.Adam})
        assert isinstance(loaded.optimizer, tf.keras.optimizers.Adam)
        assert not hasattr(loaded.optimizer, "_hvd_op")

    def test_load_model_roundtrips_distributed_optimizer(self, tmp_path):
        # Saving while compiled WITH DistributedOptimizer stores class
        # name "Distributed<Base>"; load_model must resolve that too.
        model = _tiny_model()
        model.compile(
            optimizer=hvd_keras.DistributedOptimizer(
                tf.keras.optimizers.SGD(0.1)),
            loss="mse")
        path = str(tmp_path / "m.keras")
        model.save(path)
        loaded = hvd_keras.load_model(path)
        assert isinstance(loaded.optimizer, tf.keras.optimizers.SGD)
        assert hasattr(loaded.optimizer, "_hvd_op")

    def test_broadcast_model(self):
        model = _tiny_model()
        before = [w.numpy().copy() for w in model.variables]
        hvd_keras.broadcast_model(model, root_rank=0)
        for b, w in zip(before, model.variables):
            np.testing.assert_allclose(b, w.numpy())


class TestKerasCallbacks:
    def test_broadcast_callback_fires_once(self):
        model = _tiny_model()
        model.compile(optimizer=hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.05)), loss="mse")
        cb = hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0)
        x = np.zeros((8, 4), np.float32)
        y = np.zeros((8, 2), np.float32)
        model.fit(x, y, epochs=1, batch_size=4, verbose=0, callbacks=[cb])
        assert cb.broadcast_done

    def test_metric_average_callback(self):
        cb = hvd_keras.callbacks.MetricAverageCallback()
        logs = {"loss": 2.0, "acc": 0.5}
        cb.on_epoch_end(0, logs)
        assert logs["loss"] == pytest.approx(2.0)
        assert logs["acc"] == pytest.approx(0.5)

    def test_warmup_callback_ramps_lr(self):
        model = _tiny_model()
        model.compile(optimizer=hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.8)), loss="mse")
        cb = hvd_keras.callbacks.LearningRateWarmupCallback(
            initial_lr=0.8, warmup_epochs=2, steps_per_epoch=2)
        x = np.zeros((8, 4), np.float32)
        y = np.zeros((8, 2), np.float32)
        model.fit(x, y, epochs=2, batch_size=4, verbose=0, callbacks=[cb])
        # After warmup completes the LR reaches the scaled target.
        assert float(model.optimizer.learning_rate.numpy()) == \
            pytest.approx(0.8, rel=1e-5)

    def test_schedule_callback_staircase(self):
        model = _tiny_model()
        model.compile(optimizer=hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.4)), loss="mse")
        cb = hvd_keras.callbacks.LearningRateScheduleCallback(
            initial_lr=0.4, multiplier=lambda e: 0.1 ** e, start_epoch=0)
        x = np.zeros((8, 4), np.float32)
        y = np.zeros((8, 2), np.float32)
        model.fit(x, y, epochs=2, batch_size=8, verbose=0, callbacks=[cb])
        assert float(model.optimizer.learning_rate.numpy()) == \
            pytest.approx(0.04, rel=1e-5)


class TestSyncBatchNormalization:
    """Reference: horovod/tensorflow/sync_batch_norm.py — cross-rank
    moments; identical per-rank data makes sync == local."""

    def test_matches_local_bn_on_identical_data(self):
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        tf.random.set_seed(0)
        x = tf.random.normal((16, 4))
        sbn = hvd_tf.SyncBatchNormalization(axis=-1)
        bn = tf.keras.layers.BatchNormalization(axis=-1)
        out_s = sbn(x, training=True)
        out_p = bn(x, training=True)
        np.testing.assert_allclose(out_s.numpy(), out_p.numpy(),
                                   atol=1e-5)

    def test_inference_mode(self):
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        sbn = hvd_tf.SyncBatchNormalization(axis=-1)
        x = tf.ones((8, 3))
        sbn(x, training=True)
        out = sbn(x, training=False)
        assert np.isfinite(out.numpy()).all()

    def test_gradients_match_local_bn_on_identical_data(self):
        # Regression: the numpy bridge severs gradients; the straight-
        # through moments must preserve the local gradient path, so with
        # identical per-rank data grads == plain BN grads exactly.
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        tf.random.set_seed(1)
        x = tf.random.normal((12, 3))
        sbn = hvd_tf.SyncBatchNormalization(axis=-1)
        bn = tf.keras.layers.BatchNormalization(axis=-1)
        sbn(x, training=True), bn(x, training=True)  # build
        bn.set_weights(sbn.get_weights())
        with tf.GradientTape() as t1:
            t1.watch(x)
            l1 = tf.reduce_sum(tf.square(sbn(x, training=True)))
        with tf.GradientTape() as t2:
            t2.watch(x)
            l2 = tf.reduce_sum(tf.square(bn(x, training=True)))
        g1 = t1.gradient(l1, x)
        g2 = t2.gradient(l2, x)
        np.testing.assert_allclose(g1.numpy(), g2.numpy(), atol=1e-4)

    def test_no_nan_on_large_mean_tiny_variance(self):
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        x = tf.fill((32, 4), 100.0) + tf.random.normal((32, 4)) * 1e-4
        out = hvd_tf.SyncBatchNormalization(axis=-1)(x, training=True)
        assert np.isfinite(out.numpy()).all()


class TestTensorFlowElasticState:
    """Reference: tensorflow/elastic.py TensorFlowState (raw variables,
    custom training loops)."""

    def test_save_restore_and_sync(self):
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        v1 = tf.Variable([1.0, 2.0])
        v2 = tf.Variable(3.0)
        state = hvd_tf.elastic.TensorFlowState(
            variables=[v1, v2], step=5)
        v1.assign([9.0, 9.0])
        v2.assign(0.0)
        state.step = 11
        state.restore()
        np.testing.assert_allclose(v1.numpy(), [1.0, 2.0])
        assert float(v2.numpy()) == 3.0
        assert state.step == 5
        state.sync()  # size 1: values unchanged, no error
        np.testing.assert_allclose(v1.numpy(), [1.0, 2.0])


class TestTensorFlowKerasElasticState:
    """Reference: horovod/tensorflow/elastic.py TensorFlowKerasState."""

    def _model(self, tf):
        m = tf.keras.Sequential([tf.keras.layers.Dense(2)])
        m(tf.ones((1, 3)))  # build
        return m

    def test_save_restore_roundtrip(self):
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        m = self._model(tf)
        state = hvd_tf.elastic.TensorFlowKerasState(m, epoch=4)
        saved = [w.copy() for w in m.get_weights()]
        m.set_weights([w * 0 + 7 for w in m.get_weights()])
        state.epoch = 9
        state.restore()
        for got, want in zip(m.get_weights(), saved):
            np.testing.assert_allclose(got, want)
        assert state.epoch == 4

    def test_sync_runs(self):
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        m = self._model(tf)
        state = hvd_tf.elastic.TensorFlowKerasState(m, epoch=2)
        state.sync()
        assert state.epoch == 2

    def test_optimizer_state_roundtrip(self):
        # Regression: Keras 3 exposes optimizer.variables as a property.
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        m = self._model(tf)
        opt = tf.keras.optimizers.SGD(0.1, momentum=0.9)
        with tf.GradientTape() as t:
            loss = tf.reduce_sum(m(tf.ones((2, 3))) ** 2)
        opt.apply_gradients(zip(t.gradient(loss, m.trainable_variables),
                                m.trainable_variables))
        state = hvd_tf.elastic.TensorFlowKerasState(m, optimizer=opt,
                                                    epoch=1)
        snap = [v.copy() for v in state._opt_vars]
        with tf.GradientTape() as t:
            loss = tf.reduce_sum(m(tf.ones((2, 3))) ** 2)
        opt.apply_gradients(zip(t.gradient(loss, m.trainable_variables),
                                m.trainable_variables))
        state.restore()
        for got, want in zip(state._opt_variables(), snap):
            np.testing.assert_allclose(got, want)
        state.sync()


class TestDlpackBridge:
    """The device-resident bridge (tensorflow/_bridge.py): TF tensors
    enter the collective core as dlpack-adopted jax.Arrays (zero-copy),
    and come back with caller-visible dtypes restored."""

    def test_tf_to_jax_is_jax_array(self):
        import jax

        from horovod_tpu.tensorflow._bridge import tf_to_jax

        for dtype in (tf.float32, tf.bfloat16, tf.int32, tf.bool):
            t = tf.cast(tf.constant([[1, 0], [3, 4]]), dtype)
            a = tf_to_jax(t)
            assert isinstance(a, jax.Array), dtype
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), tf.cast(t, tf.float32).numpy())

    def test_tf_to_jax_dtype_fidelity(self):
        """bf16 crosses as bf16 (no float upcast through a numpy detour);
        the wire stays half-width end to end."""
        import jax.numpy as jnp

        from horovod_tpu.tensorflow._bridge import tf_to_jax

        t = tf.cast(tf.constant([1.5, 2.5]), tf.bfloat16)
        assert tf_to_jax(t).dtype == jnp.bfloat16

    def test_variable_and_indexed_slices_densify(self):
        import jax

        from horovod_tpu.tensorflow._bridge import tf_to_jax

        v = tf.Variable([1.0, 2.0])
        assert isinstance(tf_to_jax(v), jax.Array)
        sl = tf.IndexedSlices(
            values=tf.ones((1, 2)), indices=tf.constant([1]),
            dense_shape=tf.constant([3, 2]))
        d = tf_to_jax(sl)
        assert d.shape == (3, 2)

    def test_jax_to_tf_restores_dtype(self):
        import jax.numpy as jnp

        from horovod_tpu.tensorflow._bridge import jax_to_tf

        out = jax_to_tf(jnp.arange(4, dtype=jnp.int32),
                        like=tf.constant([0], dtype=tf.int64))
        assert out.dtype == tf.int64
        out = jax_to_tf(jnp.ones(3, jnp.float32))
        assert out.dtype == tf.float32

    def test_collective_result_stays_device_resident(self):
        """The op closures must not force a host round-trip: allreduce's
        internal fn output is a jax.Array (the only host touch is the
        final jax_to_tf)."""
        import jax

        from horovod_tpu.ops import collectives as C

        a = C.allreduce(np.ones(4, np.float32))
        assert isinstance(a, jax.Array)


class TestTfScalarAllgather:
    def test_scalar_allgather_forward(self):
        import tensorflow as tf

        y = hvd_tf.allgather(tf.constant(3.0))
        assert y.shape == (hvd_tf.size(),)

    def test_scalar_allgather_gradient(self):
        import tensorflow as tf

        x = tf.Variable(2.0)
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd_tf.allgather(x))
        g = tape.gradient(y, x)
        np.testing.assert_allclose(float(g), float(hvd_tf.size()))


class TestTfGroupedGradient:
    def test_grouped_allreduce_gradient(self):
        import tensorflow as tf

        a = tf.Variable(tf.ones((3,)))
        b = tf.Variable(tf.ones((2, 2)))
        with tf.GradientTape() as tape:
            outs = hvd_tf.grouped_allreduce([a * 2.0, b * 5.0])
            y = tf.reduce_sum(outs[0]) + tf.reduce_sum(outs[1])
        ga, gb = tape.gradient(y, [a, b])
        np.testing.assert_allclose(ga.numpy(), np.full((3,), 2.0))
        np.testing.assert_allclose(gb.numpy(), np.full((2, 2), 5.0))


class TestTfAlltoallSplitsGradient:
    def test_splits_alltoall_gradient(self):
        import tensorflow as tf

        n = hvd_tf.size()
        x = tf.Variable(tf.ones((n, 2)))
        splits = tf.constant([1] * n, dtype=tf.int32)
        with tf.GradientTape() as tape:
            out, recv_splits = hvd_tf.alltoall(x * 4.0, splits=splits)
            y = tf.reduce_sum(out)
        g = tape.gradient(y, x)
        assert recv_splits.shape == (n,)
        np.testing.assert_allclose(g.numpy(), np.full((n, 2), 4.0))


class TestGradientPredivide:
    """Reference: gradient_predivide_factor splits the averaging around
    the sum (prescale 1/f, postscale f/size) — the NET result is still
    the exact average for any f."""

    def test_predivide_preserves_average(self):
        import tensorflow as tf

        v = tf.Variable(tf.ones((4,)))
        with tf.GradientTape() as t0:
            y0 = tf.reduce_sum(v * 3.0)
        plain = hvd_tf.DistributedGradientTape(t0).gradient(y0, [v])[0]

        with tf.GradientTape() as t1:
            y1 = tf.reduce_sum(v * 3.0)
        pre = hvd_tf.DistributedGradientTape(
            t1, gradient_predivide_factor=2.0).gradient(y1, [v])[0]
        np.testing.assert_allclose(pre.numpy(), plain.numpy(), rtol=1e-6)

    def test_predivide_requires_average(self):
        import tensorflow as tf

        v = tf.Variable(tf.ones((4,)))
        with tf.GradientTape() as t:
            y = tf.reduce_sum(v * 3.0)
        tape = hvd_tf.DistributedGradientTape(
            t, op=hvd_tf.Sum, gradient_predivide_factor=2.0)
        with pytest.raises(ValueError, match="requires op=Average"):
            tape.gradient(y, [v])

    def test_signature_parity_kwargs_accepted(self):
        import tensorflow as tf

        # Reference-signature kwargs are accepted (and ignored).
        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.1), name="dist",
            device_dense="/gpu:0", device_sparse="/cpu:0",
            num_groups=2, groups=None)
        assert opt is not None


class TestElasticKerasCallbacks:
    """Reference: horovod/_keras/elastic.py callback trio + KerasState
    (horovod/tensorflow/keras/elastic.py)."""

    def _fit(self, callbacks, epochs=2, batches=4):
        tf.keras.utils.set_random_seed(0)
        model = _tiny_model()
        model.compile(optimizer=tf.keras.optimizers.SGD(0.01), loss="mse")
        x = np.random.RandomState(0).randn(batches * 4, 4).astype(np.float32)
        y = np.random.RandomState(1).randn(batches * 4, 2).astype(np.float32)
        model.fit(x, y, epochs=epochs, batch_size=4, verbose=0,
                  callbacks=callbacks)
        return model

    def test_commit_state_callback_commits_every_n(self):
        import horovod_tpu.tensorflow.keras.elastic as ke

        commits = []

        class SpyState(ke.KerasState):
            def commit(self):
                commits.append(1)
                super().commit()

        state = SpyState(batch=0, epoch=0)
        self._fit([ke.CommitStateCallback(state, batches_per_commit=2)],
                  epochs=1, batches=4)
        assert len(commits) == 2  # 4 batches / commit every 2

    def test_update_batch_and_epoch_state(self):
        import horovod_tpu.tensorflow.keras.elastic as ke

        state = ke.KerasState(batch=0, epoch=0)
        seen = []

        class Spy(tf.keras.callbacks.Callback):
            def on_batch_end(self, batch, logs=None):
                seen.append(state.batch)

        self._fit([ke.UpdateBatchStateCallback(state), Spy(),
                   ke.UpdateEpochStateCallback(state)],
                  epochs=2, batches=3)
        assert state.epoch == 2
        assert state.batch == 0          # reset at epoch end
        assert max(seen) == 3            # tracked in-epoch progress

    def test_keras_state_save_restore_roundtrip(self):
        import horovod_tpu.tensorflow.keras.elastic as ke

        model = _tiny_model()
        model.compile(optimizer=tf.keras.optimizers.SGD(0.01), loss="mse")
        state = ke.KerasState(model, epoch=3)
        w0 = [w.copy() for w in model.get_weights()]
        state.save()
        model.set_weights([w * 0 for w in w0])
        state.epoch = 7
        state.restore()
        for a, b in zip(model.get_weights(), w0):
            np.testing.assert_array_equal(a, b)
        assert state.epoch == 3

    def test_standalone_keras_namespace(self):
        import horovod_tpu.keras.elastic as ske
        import horovod_tpu.tensorflow.keras.elastic as ke

        assert ske.CommitStateCallback is ke.CommitStateCallback
        assert ske.KerasState is ke.KerasState

    def test_keras_state_defaults_to_model_optimizer(self):
        # Reference: TensorFlowKerasState snapshots a compiled model's
        # own optimizer (slot variables) unless one is passed explicitly.
        import horovod_tpu.tensorflow.keras.elastic as ke

        model = _tiny_model()
        model.compile(optimizer=tf.keras.optimizers.Adam(1e-3), loss="mse")
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
        model.train_on_batch(x, y)
        state = ke.KerasState(model)
        assert state.optimizer is model.optimizer
        state.save()
        it0 = int(model.optimizer.iterations.numpy())
        model.train_on_batch(x, y)
        state.restore()
        assert int(model.optimizer.iterations.numpy()) == it0


class TestPartialDistributedOptimizer:
    """Reference horovod/tensorflow/keras PartialDistributedOptimizer:
    local layers' variables skip the allreduce."""

    def test_local_layer_grads_skip_sync(self, monkeypatch):
        import horovod_tpu.tensorflow.keras as K

        seen = []
        orig = K._allreduce_grads

        def spy(grads, *a, **kw):
            seen.append([g is None for g in grads])
            return orig(grads, *a, **kw)

        monkeypatch.setattr(K, "_allreduce_grads", spy)
        tf.keras.utils.set_random_seed(0)
        local = tf.keras.layers.Dense(2, name="local_head")
        model = tf.keras.Sequential([
            tf.keras.layers.Input((4,)),
            tf.keras.layers.Dense(8, activation="relu"),
            local,
        ])
        opt = hvd_keras.PartialDistributedOptimizer(
            tf.keras.optimizers.SGD(0.1), local_layers=[local])
        model.compile(optimizer=opt, loss="mse")
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
        w_local_before = [w.numpy().copy() for w in local.weights]
        model.train_on_batch(x, y)
        # the allreduce saw None exactly at the local layer's grads
        assert seen and sum(seen[-1]) == len(local.trainable_variables)
        # and the local layer still TRAINED (raw gradient applied)
        changed = any(not np.allclose(a.numpy(), b)
                      for a, b in zip(local.weights, w_local_before))
        assert changed

    def test_no_local_layers_is_plain_distributed(self):
        opt = hvd_keras.PartialDistributedOptimizer(
            tf.keras.optimizers.SGD(0.1))
        v = tf.Variable([1.0, 1.0])
        opt.apply_gradients([(tf.constant([2.0, 2.0]), v)])
        np.testing.assert_allclose(v.numpy(), [0.8, 0.8])

    def test_variables_accepted_directly(self, monkeypatch):
        import horovod_tpu.tensorflow.keras as K

        seen = []
        orig = K._allreduce_grads

        def spy(grads, *a, **kw):
            seen.append([g is None for g in grads])
            return orig(grads, *a, **kw)

        monkeypatch.setattr(K, "_allreduce_grads", spy)
        v1 = tf.Variable([1.0, 1.0])
        v2 = tf.Variable([2.0, 2.0])
        opt = hvd_keras.PartialDistributedOptimizer(
            tf.keras.optimizers.SGD(0.1), local_layers=[v2])
        opt.apply_gradients([(tf.constant([1.0, 1.0]), v1),
                             (tf.constant([1.0, 1.0]), v2)])
        assert seen[-1] == [False, True]
