"""REAL cross-process collective tests: two OS processes bootstrap
`jax.distributed` (CPU backend, gloo cross-process collectives) through
the launcher and move actual tensors between processes.

Reference parity: SURVEY.md §4 — the bulk of Horovod's test suite runs
under a real 2-process `horovodrun`; this file is that pattern, end to
end through `horovodrun_tpu`'s exec path (rendezvous server, env
injection, coordinator bootstrap, collectives, teardown).
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO_ROOT, "tests", "data", "multiproc_main.py")


def _launch(np_, out_dir, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["HVD_TEST_OUT"] = str(out_dir)
    env["JAX_PLATFORMS"] = "cpu"
    # Workers must see exactly one local CPU device each so the global
    # mesh is one-device-per-process.
    env.pop("XLA_FLAGS", None)
    # The consistency checker must be TRANSPARENT for correct programs —
    # including ragged allgather and concurrent disjoint process sets.
    env["HOROVOD_COLLECTIVE_CONSISTENCY_CHECK"] = "1"
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
         "python", WORKER],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO_ROOT)


@pytest.mark.integration
class TestCrossProcessCollectives:
    def test_two_process_allreduce(self, tmp_path):
        r = _launch(2, tmp_path)
        assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
        results = {}
        for rank in (0, 1):
            path = tmp_path / f"rank{rank}.json"
            assert path.exists(), \
                f"rank {rank} wrote no result:\n{r.stdout}\n{r.stderr}"
            results[rank] = json.loads(path.read_text())
        for rank, res in results.items():
            assert res["size"] == 2
            # sum over ranks: [1,2]*1 + [1,2]*2 = [3,6]
            assert res["allreduce_sum"] == [3.0, 6.0]
            # avg of rank values 0,1 = 0.5
            assert res["allreduce_avg"] == [0.5, 0.5, 0.5]
            # root 0's value
            assert res["broadcast"] == [100.0]
            # concat in rank order
            assert res["allgather"] == [[0.0, 0.0], [1.0, 1.0]]
            # ragged: rank 0 one row, rank 1 two rows
            assert res["allgather_ragged"] == [0.0, 1.0, 1.0]
            # rank r's received chunk from sender s = s
            assert res["alltoall"] == [0.0, 1.0]
            # summed tensor rows, one per rank
            assert res["reducescatter"] == [3.0, 3.0]
        # Singleton process sets at np=2: each rank reduces alone.
        assert results[0]["ps_sum"] == [1.0]
        assert results[1]["ps_sum"] == [2.0]
        # Checkpoint: rank 0 wrote; both ranks restored rank 0's state.
        for rank in (0, 1):
            assert results[rank]["ckpt"] == [1.0, 1.0, 1.0]
            assert results[rank]["ckpt_latest"] == 1

    @pytest.mark.slow
    def test_four_process_collectives(self, tmp_path):
        """np=4 (reference floor is 2 processes; SURVEY §4 says go
        beyond): mesh order, every collective, and process-set subsets
        that span non-adjacent processes."""
        self._run_n_process(4, tmp_path, timeout=420)

    @pytest.mark.slow
    def test_eight_process_collectives(self, tmp_path):
        """np=8: contiguous-rank/mesh-order assumptions at the size the
        virtual-device tests simulate, with real processes."""
        self._run_n_process(8, tmp_path, timeout=560)

    def _run_n_process(self, n, tmp_path, timeout):
        r = _launch(n, tmp_path, timeout=timeout)
        assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
        results = {}
        for rank in range(n):
            path = tmp_path / f"rank{rank}.json"
            assert path.exists(), \
                f"rank {rank} wrote no result:\n{r.stdout}\n{r.stderr}"
            results[rank] = json.loads(path.read_text())
        total = sum(range(1, n + 1))  # sum of each rank's (rank+1)
        for rank, res in results.items():
            assert res["size"] == n
            assert res["allreduce_sum"] == [1.0 * total, 2.0 * total]
            avg = sum(range(n)) / n
            assert res["allreduce_avg"] == [avg] * 3
            assert res["broadcast"] == [100.0]
            assert res["allgather"] == [[float(s)] * 2 for s in range(n)]
            assert res["allgather_ragged"] == [
                float(s) for s in range(n) for _ in range(s + 1)]
            # mesh/rank order: received chunk s comes from global rank s.
            assert res["alltoall"] == [float(s) for s in range(n)]
            assert res["reducescatter"] == [float(total)] * 2
        # Process sets spanning non-adjacent processes (evens/odds),
        # computed concurrently: each rank sums (r+1) within its set.
        even_sum = float(sum(r + 1 for r in range(0, n, 2)))
        odd_sum = float(sum(r + 1 for r in range(1, n, 2)))
        for rank in range(n):
            expected = even_sum if rank % 2 == 0 else odd_sum
            assert results[rank]["ps_sum"] == [expected], results[rank]


JOIN_WORKER = os.path.join(REPO_ROOT, "tests", "data", "join_main.py")


@pytest.mark.integration
class TestJoinMultiprocess:
    """True join under real multi-process collectives: rank 0 exhausts
    its data first and services rank 1's remaining collectives with zero
    contributions (signature mirroring over the control plane).
    Reference: test_torch.py join cases."""

    def test_uneven_batches_join(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["HVD_TEST_OUT"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        # Regression: the consistency checker must not deadlock against
        # join mode (it defers to join's own signature protocol).
        env["HOROVOD_COLLECTIVE_CONSISTENCY_CHECK"] = "1"
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "python", JOIN_WORKER],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=REPO_ROOT)
        assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
        res = {}
        for rank in (0, 1):
            path = tmp_path / f"rank{rank}.json"
            assert path.exists(), f"no result for rank {rank}:\n{r.stdout}"
            res[rank] = json.loads(path.read_text())
        # Rank 0: 3 batches, both ranks active -> avg of (1,2) = 1.5.
        assert res[0]["averages"] == [1.5, 1.5, 1.5]
        # Rank 1: first 3 steps averaged with rank 0 (1.5); after rank 0
        # joins, the average covers rank 1 alone (2.0) — NOT dragged to
        # 1.0 by a zero contribution.
        assert res[1]["averages"] == [1.5, 1.5, 1.5, 2.0, 2.0]
        # Rank 1 joined last.
        assert res[0]["last_joined"] == 1
        assert res[1]["last_joined"] == 1
        # Collectives issued while rank 0 was joined (mirrored with zero
        # contributions — JoinOp covers every enqueue type):
        # reducescatter Average over active count 1 → rank 1's own row.
        assert res[1]["rs"] == [20.0]
        # Fixed alltoall: rank 0 contributes zeros; rank 1 receives
        # [rank0's chunk (0), its own chunk (5)].
        assert res[1]["a2a"] == [0.0, 5.0]
        # Splits alltoall: joined rank sends zero splits — rank 1 receives
        # only its own 2 elements, recv splits [0, 2].
        assert res[1]["a2av"] == [2.0, 3.0]
        assert res[1]["a2av_splits"] == [0, 2]


HIER_WORKER = os.path.join(REPO_ROOT, "tests", "data",
                           "hierarchical_main.py")


@pytest.mark.integration
class TestHierarchicalCrossProcess:
    """Two-tier mesh with the slow tier on a REAL process boundary:
    np=2 processes x 4 virtual devices each fold into the 2x4
    ("dcn", "hvd") hierarchical mesh, so the DCN legs (including the
    int8 wire and the ZeRO-1 reduce-scatter/allgather pair) cross the
    gloo transport instead of staying host-local like the
    single-process suites."""

    def test_two_tier_collectives_cross_process(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["HVD_TEST_OUT"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        # The worker pins its own 4-device XLA_FLAGS before importing
        # jax; drop the parent's count=8 flag anyway for hygiene.
        env.pop("XLA_FLAGS", None)
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "python", HIER_WORKER],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO_ROOT)
        assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
        for pidx in (0, 1):
            path = tmp_path / f"rank{pidx}.json"
            assert path.exists(), \
                f"process {pidx} wrote no result:\n{r.stdout}\n{r.stderr}"
            res = json.loads(path.read_text())
            assert res["size"] == 8
            # Exact two-level == flat, bit for bit (integer-valued f32).
            assert res["hier_exact_bitwise"], res
            # ZeRO-1 substrate: RS+AG reassembles the exact flat sum.
            assert res["rs_ag_bitwise"], res
            # int8 DCN wire engaged (error nonzero) and bounded.
            assert 0.0 < res["int8_err"] < res["ref_scale"] / 25, res


ZERO_WORKER = os.path.join(REPO_ROOT, "tests", "data", "zero_main.py")


@pytest.mark.integration
class TestZeroCrossProcess:
    """ZeRO-2 and ZeRO-3 end-to-end across a REAL process boundary:
    np=2 gloo workers run two accumulation windows per stage, so every
    per-pass reduce-scatter, just-in-time param gather, and update
    allgather crosses the transport.  The contract under test is the
    ladder's replica consistency: final params bitwise-identical across
    ranks for every stage, stage 2 bitwise-equal to stage 1 +
    early_reduction (integer f32 grads, power-of-two world size), and
    the int8 gather-wire stage-3 variant still rank-identical with
    bounded wire error."""

    def test_zero2_zero3_end_to_end(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["HVD_TEST_OUT"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        # One CPU device per process: the shard exchange must cross gloo.
        env.pop("XLA_FLAGS", None)
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "python", ZERO_WORKER],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO_ROOT)
        assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
        res = {}
        for rank in (0, 1):
            path = tmp_path / f"rank{rank}.json"
            assert path.exists(), \
                f"rank {rank} wrote no result:\n{r.stdout}\n{r.stderr}"
            res[rank] = json.loads(path.read_text())
        # Replica consistency: every stage's finals bitwise-identical
        # across the process boundary (JSON round-trips f32 exactly).
        for key in ("z1", "z2", "z3", "z3_int8"):
            assert res[0][key] == res[1][key], key
        for rank in (0, 1):
            out = res[rank]
            assert out["z2_bitwise_z1"], out
            assert out["z3_bitwise_z1"], out
            # int8 gather wire engaged: error nonzero but bounded.
            assert 0.0 < out["z3q_maxerr"] < out["z1_scale"] / 10, out
            # Stage-3 residency: ~1/2 of the replicated param bytes.
            assert out["param_resident_bytes"] <= \
                out["param_full_bytes"] // 2 + 8
        # Sanity: training moved the params.
        def _flat(x):
            if isinstance(x, list):
                for v in x:
                    yield from _flat(v)
            else:
                yield x
        assert any(v != 0.0 for leaf in res[0]["z1"]
                   for v in _flat(leaf))


STALL_WORKER = os.path.join(REPO_ROOT, "tests", "data", "stall_main.py")


@pytest.mark.integration
class TestStallInspectorNamesRanks:
    """Reference: stall_inspector.cc reports which ranks have NOT
    submitted a stalled tensor.  Rank 0 lags 8s before the second
    collective; rank 1's inspector (warn=2s) must warn AND name rank 0
    via the control-plane heartbeats; the job then completes normally."""

    def test_lagging_rank_is_named(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env["HOROVOD_STALL_CHECK_TIME_SECONDS"] = "2"
        env["STALL_TEST_SLEEP"] = "8"
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "python", STALL_WORKER],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=REPO_ROOT)
        out = r.stdout + r.stderr
        assert r.returncode == 0, f"launch failed:\n{out}"
        assert "rank 0 done" in out and "rank 1 done" in out
        assert "stalled" in out, out
        assert "Ranks behind: rank 0" in out, out


TRACE_WORKER = os.path.join(REPO_ROOT, "tests", "data",
                            "trace_timeline_main.py")


@pytest.mark.integration
class TestFleetTracerCrossProcess:
    """End-to-end fleet tracer (docs/TRACE.md): two real ranks write
    cycle-marked timelines; `python -m horovod_tpu.trace merge` joins
    them into one Perfetto trace with cross-rank flow events and
    `analyze` attributes the steps."""

    def test_merge_and_analyze_real_rank_timelines(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["HVD_TEST_OUT"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env["HOROVOD_TIMELINE"] = str(tmp_path / "tl.json")
        env["HOROVOD_TIMELINE_ALL_RANKS"] = "1"
        env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "python", TRACE_WORKER],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=REPO_ROOT)
        assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
        for rank in (0, 1):
            res = json.loads((tmp_path / f"rank{rank}.json").read_text())
            assert res["cycles"] == 3
            assert res["sums"] == [1.5, 1.5, 1.5]  # avg(1, 2) each step
        rank_files = [str(tmp_path / "tl.json"),
                      str(tmp_path / "tl.rank1.json")]
        for p in rank_files:
            assert os.path.exists(p), f"missing rank timeline {p}"

        # Merge through the real CLI.
        merged_path = tmp_path / "fleet_trace.json"
        m = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.trace", "merge",
             *rank_files, "-o", str(merged_path)],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO_ROOT)
        assert m.returncode == 0, f"merge failed:\n{m.stdout}\n{m.stderr}"
        doc = json.loads(merged_path.read_text())
        events = doc["traceEvents"]
        assert doc["metadata"]["ranks"] == [0, 1]
        assert {e["pid"] for e in events} == {0, 1}
        # The three CYCLE_n barriers each link the two ranks.
        starts = [e for e in events if e["ph"] == "s"]
        finishes = [e for e in events if e["ph"] == "f"]
        assert len(starts) >= 3 and len(starts) == len(finishes)
        assert doc["metadata"]["flow_events"] == len(starts) * 2
        cycle_names = {e["name"] for e in events if e["ph"] == "i"}
        assert {"CYCLE_1", "CYCLE_2", "CYCLE_3"} <= cycle_names

        # Analyze through the real CLI.
        a = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.trace", "analyze",
             *rank_files],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO_ROOT)
        assert a.returncode == 0, f"analyze failed:\n{a.stdout}\n{a.stderr}"
        report = json.loads(a.stdout)
        assert report["summary"]["ranks"] == [0, 1]
        assert report["summary"]["steps_analyzed"] == 3
        assert all(s["skew_ms"] >= 0 for s in report["steps"])
        # The eager allreduces appear as attributed collective buckets.
        assert any(s["buckets"] for s in report["steps"]), report


FLEET_WORKER = os.path.join(REPO_ROOT, "tests", "data",
                            "fleet_metrics_main.py")


@pytest.mark.integration
class TestMetricsFleetViewCrossProcess:
    """Metrics fleet view under real processes (docs/METRICS.md): each
    worker binds an ephemeral scrape endpoint (HOROVOD_METRICS_PORT=0),
    publishes its snapshot to the rendezvous KV, and merges BOTH ranks'
    snapshots into the rendered cluster view."""

    def test_kv_merge_and_ephemeral_exposition(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["HVD_TEST_OUT"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env["HOROVOD_METRICS_PORT"] = "0"
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "python", FLEET_WORKER],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=REPO_ROOT)
        assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
        results = {}
        for rank in (0, 1):
            path = tmp_path / f"rank{rank}.json"
            assert path.exists(), \
                f"rank {rank} wrote no result:\n{r.stdout}\n{r.stderr}"
            results[rank] = json.loads(path.read_text())
        # Ephemeral ports bound and distinct; scrape served Prometheus.
        assert results[0]["port"] != results[1]["port"]
        for rank, res in results.items():
            assert res["port"] > 0
            assert res["scrape_has_calls"] and res["scrape_has_help"]
            # KV fleet merge saw BOTH ranks' snapshots.
            assert sorted(res["fleet_ranks"]) == [0, 1]
            # Counters summed across ranks: each rank did >= 1 collective.
            assert res["calls_total"] >= 2
            # Gauges stay per-rank in the merge.
            assert res["cp_by_rank"] == {"0": 1.5, "1": 2.5}
            assert res["render"].startswith("fleet view: 2 rank(s)")
            assert "step critical path (ms): rank0=1.5  rank1=2.5" in (
                res["render"])


CC_WORKER = os.path.join(REPO_ROOT, "tests", "data", "consistency_main.py")


@pytest.mark.integration
class TestCollectiveConsistencyCheck:
    """Semantic race detection (reference: controller.cc duplicate-name
    / mismatched-shape errors): under the debug flag, divergent
    collectives fail fast with a per-rank signature dump instead of
    hanging the compiled collective."""

    def _launch(self, mode):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env["HOROVOD_COLLECTIVE_CONSISTENCY_CHECK"] = "1"
        env["CC_TEST_MODE"] = mode
        return subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "python", CC_WORKER],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=REPO_ROOT)

    def test_matching_collectives_pass(self):
        r = self._launch("match")
        out = r.stdout + r.stderr
        assert r.returncode == 0, out
        assert "rank 0 done" in out and "rank 1 done" in out

    def test_mismatched_shape_fails_fast_with_dump(self):
        r = self._launch("mismatch")
        out = r.stdout + r.stderr
        assert r.returncode != 0
        assert "consistency check FAILED" in out, out
        assert "process 0:" in out and "process 1:" in out, out


RESHARD_WORKER = os.path.join(REPO_ROOT, "tests", "data",
                              "reshard_main.py")


@pytest.mark.integration
class TestReshardCrossProcess:
    """Live resharding across a REAL process boundary (docs/RESHARD.md):
    np=2 gloo workers build genuine ZeRO-3 state (mid-window stage-2
    accumulation, adam rows, generation-stamped EF residuals), then
    shrink 2→1 and grow 1→2 through the peak-bounded chunk mover.  The
    contract: the live redistribution is BITWISE-identical to the legacy
    checkpoint-restore-then-restack path, the measured staging peak
    stays under the configured ceiling, and an injected `reshard.peer_die`
    mid-publish degrades every rank to the old restore path with the
    guard digest verifying the restored state."""

    def test_shrink_grow_and_peer_death(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["HVD_TEST_OUT"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "python", RESHARD_WORKER],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO_ROOT)
        assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
        res = {}
        for rank in (0, 1):
            path = tmp_path / f"rank{rank}.json"
            assert path.exists(), \
                f"rank {rank} wrote no result:\n{r.stdout}\n{r.stderr}"
            res[rank] = json.loads(path.read_text())
        # Shrink: live == local restack == from-checkpoint restore,
        # peak ASSERTED under the ceiling, chunking actually engaged.
        assert res[0]["shrink_live_eq_local"], res[0]
        assert res[0]["shrink_live_eq_restore"], res[0]
        for rank in (0, 1):
            out = res[rank]
            assert out["shrink_peak_ok"], out
            assert 0 < out["shrink_peak"] <= out["peak_ceiling"], out
            assert out["shrink_multichunk"], out
        # Grow: compat restack == local fold, rows round-trip bitwise,
        # and the cross-replica guard digest agrees.
        for rank in (0, 1):
            out = res[rank]
            assert out["grow_bitwise"], out
            assert out["grow_rows_roundtrip"], out
            assert out["grow_digest_mismatch"] is None, out
            # The elastic state API end to end (same-N reshard is
            # identity, scalars broadcast, step survives).
            assert out["class_rows_bitwise"], out
            assert out["class_state_bitwise"], out
            assert out["class_step"] == 7, out
            # Peer death: every rank degrades, then the legacy restore
            # path reproduces the pre-reshard state bitwise.
            assert out["die_degraded"], out
            assert out["die_restore_bitwise"], out
            assert out["die_restore_digest_mismatch"] is None, out
        assert res[1]["die_points_hit"] == 1, res[1]
        assert res[0]["die_points_hit"] == 0, res[0]


CHAOS_WORKER = os.path.join(REPO_ROOT, "tests", "data", "chaos_main.py")


def _launch_chaos(np_, out_dir, generations, steps_per_gen,
                  extra_env=None, timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["HVD_TEST_OUT"] = str(out_dir)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.update({
        # Per-rank cycle-marked timelines feed the online windows; the
        # Python writer keeps partial files readable mid-run.
        "HOROVOD_TIMELINE": str(out_dir / "tl.json"),
        "HOROVOD_TIMELINE_ALL_RANKS": "1",
        "HOROVOD_TIMELINE_MARK_CYCLES": "1",
        "HOROVOD_TIMELINE_DISABLE_NATIVE": "1",
        # Online autotuner against the merged-trace objective.
        "HOROVOD_AUTOTUNE": "1",
        "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
        # Reaction policy tight enough to fire inside the soak.
        "HOROVOD_STRAGGLER_PATIENCE": "2",
        "HOROVOD_STRAGGLER_COOLDOWN": "1",
        "HOROVOD_CHAOS_GENERATIONS": str(generations),
        "HOROVOD_CHAOS_STEPS_PER_GEN": str(steps_per_gen),
        "HVD_CHAOS_SEED": "7",
    })
    env.update(extra_env or {})
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
         "python", CHAOS_WORKER],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO_ROOT)
    assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
    res = {}
    for rank in range(np_):
        path = out_dir / f"rank{rank}.json"
        assert path.exists(), \
            f"rank {rank} wrote no result:\n{r.stdout}\n{r.stderr}"
        res[rank] = json.loads(path.read_text())
    return res


def _assert_soak_invariants(res, np_):
    """The re-convergence contract every soak run must satisfy."""
    for rank, out in res.items():
        assert not out["split_brain"], out
        assert out["final_digest_mismatch"] is None, out
        for ev in out["events"]:
            assert ev["outcome"] in ("recovered", "degraded"), ev
            assert ev["mttr_ms"] >= 0, ev
    # Final params bitwise-identical across every surviving rank.
    for rank in range(1, np_):
        assert res[rank]["final_w"] == res[0]["final_w"], \
            f"rank {rank} params diverged from rank 0"
    # All ranks observed the identical event stream (lockstep plan).
    for rank in range(1, np_):
        assert ([ (e["kind"], e["gen"], e["step"]) for e in
                  res[rank]["events"] ]
                == [ (e["kind"], e["gen"], e["step"]) for e in
                     res[0]["events"] ])
    # Online autotuner: samples flowing, best-observed objective
    # (best-so-far items/sec) non-worsening across windows.
    out0 = res[0]
    assert out0["autotune_enabled"]
    bests = [w["autotune_best"] for w in out0["windows"]
             if w["autotune_best"] is not None]
    assert bests, "autotuner never recorded a window sample"
    assert all(b2 >= b1 for b1, b2 in zip(bests, bests[1:])), bests
    samples = [w["autotune_samples"] for w in out0["windows"]]
    assert samples[-1] >= 1 and samples == sorted(samples), samples


@pytest.mark.integration
class TestChaosSoakFast:
    """np=2 tier-1 chaos soak (docs/CHAOS.md): the orchestrator itself —
    straggler block with a live reaction, one-shot guard/collective
    injections, per-generation merged-trace windows feeding the online
    autotuner — small enough for tier-1."""

    def test_two_process_soak(self, tmp_path):
        res = _launch_chaos(2, tmp_path, generations=5, steps_per_gen=4)
        _assert_soak_invariants(res, 2)
        out = res[0]
        # The straggler block armed and the blame stream fired a
        # reaction (patience 2 inside a 4-generation block).
        assert out["straggler_target"] >= 0
        assert any(r["action"] == "rebalance" for r in out["reactions"]), \
            out["reactions"]
        blamed = [w["straggler_rank"] for w in out["windows"]
                  if w["straggler_armed"]]
        assert out["straggler_target"] in blamed, out["windows"]
        # The rebalance repartition went through the LOUD re-init path.
        assert out["loud_reinits"] >= 1, out
        # Both one-shot injections of the event generation recovered.
        kinds = {e["kind"]: e for e in out["events"]}
        assert "worker_stall" in kinds and "nan_grad" in kinds, kinds
        assert kinds["nan_grad"]["outcome"] == "recovered", kinds
        assert kinds["nan_grad"]["steps_lost"] >= 1, kinds
        # Reactions were computed in lockstep on every rank.
        assert res[1]["reactions"] == out["reactions"]
        # Anomaly detectors (docs/TELEMETRY.md): the injected faults
        # are ground truth — at least one injected kind must be
        # flagged by the step-time / step-counter monitors, every trip
        # must attribute to an injection (zero false positives on
        # clean steps), and trips name the offending series.
        anom = out["anomaly"]
        assert anom["false_positives"] == 0, anom["events"]
        assert len(anom["detected_kinds"]) >= 1, anom
        assert set(anom["detected_kinds"]) <= set(anom["injected_kinds"])
        for ev in anom["events"]:
            assert ev["series"] in ("hvd_critical_path_ms",
                                    "hvd_steps_total"), ev


@pytest.mark.slow
class TestChaosSoakFleet:
    """np=4 fault-loaded soak — ISSUE 15's acceptance run: >= 5 distinct
    injected fault kinds in one run, every event digest-verified
    recovered (or deliberately degraded), per-event MTTR, straggler
    reaction fires and post-reaction skew drops, autotuner online with
    a non-worsening best objective, final params bitwise-identical."""

    def test_four_process_fault_loaded_soak(self, tmp_path):
        res = _launch_chaos(
            4, tmp_path, generations=8, steps_per_gen=5,
            extra_env={"HOROVOD_WIRE_POLICY": "bf16:65536"},
            timeout=540)
        _assert_soak_invariants(res, 4)
        out = res[0]
        # >= 5 distinct fault kinds survived in ONE run.
        assert len(out["kinds_injected"]) >= 5, out["kinds_injected"]
        recovered = {e["kind"] for e in out["events"]
                     if e["outcome"] == "recovered"}
        assert len(recovered) >= 5, out["events"]
        # Straggler reaction fired and the post-reaction merged-trace
        # ABSOLUTE wait per step dropped while the delay stayed armed
        # (skew_share is a ratio of the critical path, so collapsing to
        # one bucket can raise it even as the time lost shrinks —
        # wait_ms_per_step is the efficacy signal, see trace/measure.py).
        assert any(r["action"] == "rebalance" for r in out["reactions"])
        fired_gen = min(r["gen"] for r in out["reactions"])
        pre = [w["wait_ms_per_step"] for w in out["windows"]
               if w["straggler_armed"] and w["gen"] <= fired_gen
               and w["wait_ms_per_step"] is not None]
        post = [w["wait_ms_per_step"] for w in out["windows"]
                if w["straggler_armed"] and w["gen"] > fired_gen
                and w["wait_ms_per_step"] is not None]
        assert pre and post, out["windows"]
        assert min(post) < max(pre), (pre, post)
        assert out["loud_reinits"] >= 1, out
