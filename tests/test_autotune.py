"""Autotuner tests (reference behavior: parameter_manager.cc + optim/)."""

import math

import numpy as np
import pytest

from horovod_tpu.utils.autotune import (
    BayesianOptimizer,
    GaussianProcess,
    ParameterManager,
)


class TestGaussianProcess:
    def test_interpolates_training_points(self):
        gp = GaussianProcess(noise=1e-8)
        x = np.array([[0.0], [0.5], [1.0]])
        y = np.array([1.0, 3.0, 2.0])
        gp.fit(x, y)
        mu, sigma = gp.predict(x)
        np.testing.assert_allclose(mu, y, atol=1e-3)
        assert (sigma < 0.05).all()

    def test_uncertainty_grows_away_from_data(self):
        gp = GaussianProcess()
        gp.fit(np.array([[0.0], [0.1]]), np.array([1.0, 1.1]))
        _, s_near = gp.predict(np.array([[0.05]]))
        _, s_far = gp.predict(np.array([[0.9]]))
        assert s_far[0] > s_near[0] * 2


class TestBayesianOptimizer:
    def test_finds_peak_of_smooth_function(self):
        # Maximize f(u) = -(u - 0.7)^2: optimum at 0.7.
        bo = BayesianOptimizer(dims=1, seed=0)
        x = np.array([0.5])
        for _ in range(25):
            y = -float((x[0] - 0.7) ** 2)
            bo.observe(x, y)
            x = bo.next_sample()
        best_x, _ = bo.best
        assert abs(best_x[0] - 0.7) < 0.15

    def test_random_before_enough_data(self):
        bo = BayesianOptimizer(dims=2, seed=1)
        s = bo.next_sample()
        assert s.shape == (2,) and (0 <= s).all() and (s <= 1).all()


class TestParameterManager:
    def _drive(self, pm, rate_fn, n):
        for _ in range(n):
            pm.record_sample(rate_fn(pm.value("bucket")))

    def test_warmup_discard(self):
        pm = ParameterManager(warmup_samples=3, max_samples=10)
        pm.register("bucket", 1, 100, initial=50)
        # Warmup samples must not move the knob.
        for _ in range(3):
            pm.record_sample(100.0)
        assert pm.value("bucket") == 50

    def test_converges_and_freezes(self):
        pm = ParameterManager(warmup_samples=2, max_samples=25, seed=3)
        pm.register("bucket", 1, 100, initial=50)

        def rate(bucket):  # throughput peaks at bucket=30
            return 1000.0 - (bucket - 30.0) ** 2

        self._drive(pm, rate, 40)
        assert pm.frozen
        assert abs(pm.value("bucket") - 30) < 20

    def test_record_step_accumulates(self):
        pm = ParameterManager(warmup_samples=0, steps_per_sample=5,
                              max_samples=100)
        pm.register("bucket", 1, 100, initial=50)
        t = [0.0]

        def clock():
            t[0] += 0.1
            return t[0]

        for _ in range(11):
            pm.record_step(items=32, now=clock())
        # After 1 baseline + 2*5 steps, two samples closed out.
        assert pm._samples == 2

    def test_log_file(self, tmp_path):
        log = tmp_path / "at.csv"
        pm = ParameterManager(warmup_samples=1, max_samples=5,
                              log_file=str(log))
        pm.register("bucket", 1, 100, initial=50)
        for _ in range(8):
            pm.record_sample(123.0)
        lines = log.read_text().strip().splitlines()
        assert any(",warmup," in ln for ln in lines)
        assert any(",sample," in ln for ln in lines)
        assert any(",frozen," in ln for ln in lines)

    def test_env_gating(self, monkeypatch):
        from horovod_tpu.utils import autotune as at
        monkeypatch.delenv("HOROVOD_AUTOTUNE", raising=False)
        at.shutdown_manager()
        assert at.init_from_env() is None
        monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
        mgr = at.init_from_env()
        assert mgr is not None
        assert at.tuned_fusion_threshold(1) == 64 << 20
        at.shutdown_manager()
        assert at.tuned_fusion_threshold(7) == 7


class TestAutotuneWiredIntoTrainingPath:
    """HOROVOD_AUTOTUNE=1 must tune the money path with no user code:
    the step callable returned by `data_parallel` feeds `record_step`
    per invocation, and a new fusion-threshold proposal retraces the
    step with a different bucket count (reference: parameter_manager.cc
    is fed from the runtime and re-tunes the live job)."""

    def test_autotune_changes_bucket_count_mid_run(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.ops import collectives as C
        from horovod_tpu.utils import autotune as at

        # Tight loop: 1 warmup sample, 1 step per sample.
        monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
        monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
        monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "1")
        # Start with a tiny threshold so the initial trace has many
        # buckets; proposals range over [1MB, 256MB] -> 1 bucket.
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "8")
        at.shutdown_manager()
        assert at.init_from_env() is not None
        try:
            bucket_counts = []
            real_grouped = C.grouped_allreduce

            def counting_grouped(tensors, **kw):
                # Called once per bucket at trace time.
                bucket_counts.append(len(tensors))
                return real_grouped(tensors, **kw)

            monkeypatch.setattr(C, "grouped_allreduce", counting_grouped)

            params = {f"w{i}": jnp.ones((4,)) for i in range(6)}

            def step(params, batch):
                def loss_fn(p):
                    return sum(jnp.sum(w * batch[0]) for w in p.values())

                grads = jax.grad(loss_fn)(params)
                grads = hvd.allreduce_gradients(grads)
                return jax.tree_util.tree_map(
                    lambda w, g: w - 0.1 * g, params, grads), jnp.zeros(())

            compiled = hvd.data_parallel(
                step, batch_args=(1,), donate_args=())
            batch = hvd.shard_batch((jnp.ones((8, 4)),))
            traces_seen = set()
            for _ in range(12):
                params, _ = compiled(params, batch)
                traces_seen.add(len(bucket_counts))
            # The tuner proposed new thresholds -> the step retraced with
            # a different number of fused buckets at least once.
            assert len(bucket_counts) > 1, "step never retraced"
            assert len(set(bucket_counts)) > 1, (
                f"bucket count never changed: {bucket_counts}")
        finally:
            at.shutdown_manager()
