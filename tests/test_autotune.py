"""Autotuner tests (reference behavior: parameter_manager.cc + optim/)."""

import math

import numpy as np
import pytest

from horovod_tpu.utils.autotune import (
    BayesianOptimizer,
    GaussianProcess,
    ParameterManager,
)


class TestGaussianProcess:
    def test_interpolates_training_points(self):
        gp = GaussianProcess(noise=1e-8)
        x = np.array([[0.0], [0.5], [1.0]])
        y = np.array([1.0, 3.0, 2.0])
        gp.fit(x, y)
        mu, sigma = gp.predict(x)
        np.testing.assert_allclose(mu, y, atol=1e-3)
        assert (sigma < 0.05).all()

    def test_uncertainty_grows_away_from_data(self):
        gp = GaussianProcess()
        gp.fit(np.array([[0.0], [0.1]]), np.array([1.0, 1.1]))
        _, s_near = gp.predict(np.array([[0.05]]))
        _, s_far = gp.predict(np.array([[0.9]]))
        assert s_far[0] > s_near[0] * 2


class TestBayesianOptimizer:
    def test_finds_peak_of_smooth_function(self):
        # Maximize f(u) = -(u - 0.7)^2: optimum at 0.7.
        bo = BayesianOptimizer(dims=1, seed=0)
        x = np.array([0.5])
        for _ in range(25):
            y = -float((x[0] - 0.7) ** 2)
            bo.observe(x, y)
            x = bo.next_sample()
        best_x, _ = bo.best
        assert abs(best_x[0] - 0.7) < 0.15

    def test_random_before_enough_data(self):
        bo = BayesianOptimizer(dims=2, seed=1)
        s = bo.next_sample()
        assert s.shape == (2,) and (0 <= s).all() and (s <= 1).all()


class TestParameterManager:
    def _drive(self, pm, rate_fn, n):
        for _ in range(n):
            pm.record_sample(rate_fn(pm.value("bucket")))

    def test_warmup_discard(self):
        pm = ParameterManager(warmup_samples=3, max_samples=10)
        pm.register("bucket", 1, 100, initial=50)
        # Warmup samples must not move the knob.
        for _ in range(3):
            pm.record_sample(100.0)
        assert pm.value("bucket") == 50

    def test_converges_and_freezes(self):
        pm = ParameterManager(warmup_samples=2, max_samples=25, seed=3)
        pm.register("bucket", 1, 100, initial=50)

        def rate(bucket):  # throughput peaks at bucket=30
            return 1000.0 - (bucket - 30.0) ** 2

        self._drive(pm, rate, 40)
        assert pm.frozen
        assert abs(pm.value("bucket") - 30) < 20

    def test_record_step_accumulates(self):
        pm = ParameterManager(warmup_samples=0, steps_per_sample=5,
                              max_samples=100)
        pm.register("bucket", 1, 100, initial=50)
        t = [0.0]

        def clock():
            t[0] += 0.1
            return t[0]

        for _ in range(11):
            pm.record_step(items=32, now=clock())
        # After 1 baseline + 2*5 steps, two samples closed out.
        assert pm._samples == 2

    def test_log_file(self, tmp_path):
        log = tmp_path / "at.csv"
        pm = ParameterManager(warmup_samples=1, max_samples=5,
                              log_file=str(log))
        pm.register("bucket", 1, 100, initial=50)
        for _ in range(8):
            pm.record_sample(123.0)
        lines = log.read_text().strip().splitlines()
        assert any(",warmup," in ln for ln in lines)
        assert any(",sample," in ln for ln in lines)
        assert any(",frozen," in ln for ln in lines)

    def test_env_gating(self, monkeypatch):
        from horovod_tpu.utils import autotune as at
        monkeypatch.delenv("HOROVOD_AUTOTUNE", raising=False)
        at.shutdown_manager()
        assert at.init_from_env() is None
        monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
        mgr = at.init_from_env()
        assert mgr is not None
        assert at.tuned_fusion_threshold(1) == 64 << 20
        at.shutdown_manager()
        assert at.tuned_fusion_threshold(7) == 7
