"""True-join / uneven-data tests (reference: test_torch.py /
test_tensorflow.py join cases — a data-exhausted rank stops contributing,
averages are over the ranks still contributing, join() returns the last
joining rank).

Sim layer here exercises the masked-collective numerics on the 8-rank
mesh; tests/test_multiprocess.py::TestJoinMultiprocess exercises the real
2-process signature-mirroring path.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.ops import join as join_mod
from horovod_tpu.ops.collectives import PerRank


@pytest.fixture(autouse=True)
def clean_join_state():
    join_mod.reset()
    yield
    join_mod.reset()


def per_rank(values):
    return PerRank([jnp.asarray(v) for v in values])


class TestMaskedNumerics:
    def test_average_over_active_ranks_only(self):
        # Ranks 5,6,7 exhausted their data: averages cover ranks 0-4.
        join_mod._mark_joined([5, 6, 7])
        vals = [float(r) for r in range(8)]
        out = hvd.allreduce(per_rank([[v] for v in vals]), op=hvd.Average)
        np.testing.assert_allclose(np.asarray(out), [np.mean(vals[:5])])

    def test_sum_ignores_joined(self):
        join_mod._mark_joined([0, 1])
        out = hvd.allreduce(per_rank([[1.0]] * 8), op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(out), [6.0])

    def test_min_max_use_identity_for_joined(self):
        join_mod._mark_joined([7])
        vals = [[float(r)] for r in range(8)]  # rank 7 has the max value
        mx = hvd.allreduce(per_rank(vals), op=hvd.Max)
        np.testing.assert_allclose(np.asarray(mx), [6.0])
        join_mod.reset()
        join_mod._mark_joined([0])  # rank 0 has the min value
        mn = hvd.allreduce(per_rank(vals), op=hvd.Min)
        np.testing.assert_allclose(np.asarray(mn), [1.0])

    def test_int_sum_masked(self):
        join_mod._mark_joined([2, 3])
        out = hvd.allreduce(per_rank([[2]] * 8), op=hvd.Sum)
        assert np.asarray(out).dtype == np.int32
        np.testing.assert_array_equal(np.asarray(out), [12])

    def test_grouped_allreduce_masked(self):
        join_mod._mark_joined([4, 5, 6, 7])
        outs = hvd.grouped_allreduce(
            [per_rank([[float(r)] for r in range(8)]),
             per_rank([[2.0 * r] for r in range(8)])],
            op=hvd.Average)
        np.testing.assert_allclose(np.asarray(outs[0]), [1.5])
        np.testing.assert_allclose(np.asarray(outs[1]), [3.0])

    def test_unarmed_path_unchanged(self):
        out = hvd.allreduce(per_rank([[1.0]] * 8), op=hvd.Average)
        np.testing.assert_allclose(np.asarray(out), [1.0])

    def test_uneven_batch_training_average(self):
        """The uneven-data training contract: ranks with exhausted data
        stop influencing the gradient average."""
        grads = [[1.0, 1.0]] * 8
        # Epoch 1: everyone contributes.
        out1 = hvd.allreduce(per_rank(grads), op=hvd.Average)
        np.testing.assert_allclose(np.asarray(out1), [1.0, 1.0])
        # Epoch 2: ranks 6,7 ran out; survivors' average is unchanged by
        # the absent ranks (NOT dragged toward zero).
        join_mod._mark_joined([6, 7])
        out2 = hvd.allreduce(per_rank(grads), op=hvd.Average)
        np.testing.assert_allclose(np.asarray(out2), [1.0, 1.0])


class TestJoinApi:
    def test_join_completes_and_clears(self):
        last = hvd.join()
        assert last == 7  # all 8 sim ranks join at once; max rank returned
        # Once every rank joined the cycle completes: state clears so
        # later collectives run unmasked (reference: training continues
        # normally after join — e.g. a final metric allreduce).
        assert hvd.joined_ranks() == []

    def test_collective_after_complete_join_is_unmasked(self):
        hvd.join()
        out = hvd.allreduce(per_rank([[1.0]] * 8), op=hvd.Average)
        np.testing.assert_allclose(np.asarray(out), [1.0])
        out = hvd.allreduce(per_rank([[1.0]] * 8), op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(out), [8.0])

    def test_repeated_join_cycles(self):
        # A second uneven-data phase starts a fresh cycle.
        assert hvd.join() == 7
        assert hvd.join() == 7
        assert hvd.joined_ranks() == []

    def test_join_mode_arms(self):
        assert not join_mod.armed()
        hvd.join_mode(True)
        assert join_mod.armed()
        hvd.join_mode(False)
        assert not join_mod.armed()
