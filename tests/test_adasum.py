"""Adasum numerics vs the recursion reference model (mirrors
test_adasum_pytorch.py / test_adasum_tensorflow.py, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import PerRank
from horovod_tpu.ops.adasum import (
    adasum_in_axis, adasum_reference, adasum_tree_reduce,
)

N = 8


def grads(shape=(16,), seed=0):
    rng = np.random.RandomState(seed)
    return [rng.uniform(-1, 1, size=shape).astype(np.float32)
            for _ in range(N)]


def test_adasum_identical_inputs_is_identity():
    # adasum(a, a) == a at every tree level.
    x = np.random.RandomState(1).uniform(size=(8,)).astype(np.float32)
    out = hvd.allreduce(x, op=hvd.Adasum)
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5)


def test_adasum_orthogonal_inputs_sum():
    # Orthogonal gradients: dot = 0 → plain sum (2 ranks worth).
    ps = hvd.add_process_set([0, 1])
    try:
        a = np.array([1.0, 0.0], np.float32)
        b = np.array([0.0, 1.0], np.float32)
        out = hvd.allreduce(PerRank([a, b]), op=hvd.Adasum, process_set=ps)
        np.testing.assert_allclose(np.asarray(out), a + b, rtol=1e-5)
    finally:
        hvd.remove_process_set(ps)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_adasum_matches_reference_model(seed):
    gs = grads(seed=seed)
    out = hvd.allreduce(PerRank(gs), op=hvd.Adasum)
    expected = adasum_reference(gs)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4,
                               atol=1e-5)


def test_adasum_tree_reduce_matches_reference():
    gs = grads(seed=3)
    out = adasum_tree_reduce(jnp.stack(gs))
    np.testing.assert_allclose(np.asarray(out), adasum_reference(gs),
                               rtol=1e-4, atol=1e-5)


def test_adasum_in_axis_matches_tree(mesh):
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    gs = grads(seed=4)
    stacked = jnp.stack(gs)

    def f(x):
        return adasum_in_axis(x[0], hvd.GLOBAL_AXIS)

    sm = shard_map(f, mesh=mesh, in_specs=(P(hvd.GLOBAL_AXIS),),
                   out_specs=P(), check_vma=False)
    out = jax.jit(sm)(stacked)
    np.testing.assert_allclose(np.asarray(out), adasum_reference(gs),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("n", [3, 5, 6, 7])
def test_adasum_tree_reduce_non_pow2(n):
    # r5: non-pow-2 counts fold residuals into the head, then run the
    # balanced tree — validated against the f64 reference for every n.
    gs = grads(seed=10 + n)[:n]
    out = adasum_tree_reduce(jnp.stack(gs))
    np.testing.assert_allclose(np.asarray(out), adasum_reference(gs),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("n", [3, 5, 6])
def test_adasum_in_axis_non_pow2(n, mesh):
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    gs = grads(seed=20 + n)[:n]
    stacked = jnp.stack(gs)
    sub = Mesh(np.array(jax.devices()[:n]), (hvd.GLOBAL_AXIS,))

    def f(x):
        return adasum_in_axis(x[0], hvd.GLOBAL_AXIS)

    sm = shard_map(f, mesh=sub, in_specs=(P(hvd.GLOBAL_AXIS),),
                   out_specs=P(), check_vma=False)
    out = jax.jit(sm)(stacked)
    np.testing.assert_allclose(np.asarray(out), adasum_reference(gs),
                               rtol=1e-4, atol=1e-5)


def test_adasum_non_pow2_process_set_eager():
    ps = hvd.add_process_set([0, 1, 2])
    try:
        gs = grads(seed=9)[:3]
        out = hvd.allreduce(PerRank(gs), op=hvd.Adasum, process_set=ps)
        np.testing.assert_allclose(np.asarray(out), adasum_reference(gs),
                                   rtol=1e-4, atol=1e-5)
    finally:
        hvd.remove_process_set(ps)
