"""Fleet tracer (horovod_tpu/trace): clock alignment, cross-rank merge
with flow events, critical-path / straggler attribution, the
TraceMeasurements feedback loop, the CLI, and the fleet-view rendering
of the trace gauges (docs/TRACE.md).

The synthetic two-rank fixture is hand-computed: rank 1's wall clock
runs 500 ms ahead and it straggles into step 2 by 0.4 ms, so every
expected number below is derivable with pencil and paper from the
formulas in trace/core.py's docstring.
"""

import json
import time

import pytest

from horovod_tpu.metrics import catalog as met_catalog
from horovod_tpu.metrics import fleet
from horovod_tpu.trace import (TraceMeasurements, analyze, clock_offsets,
                               load_events, load_rank_traces, merge,
                               write_merged)
from horovod_tpu.trace.__main__ import main as trace_cli

OFFSET_US = 500000.0  # rank 1's clock runs 500 ms ahead of rank 0's


def _cycle(n, ts, rank):
    return {"name": f"CYCLE_{n}", "cat": "cycle", "ph": "i", "s": "p",
            "ts": ts, "pid": rank, "tid": "cycle", "step": n}


def _coll(ts, dur, rank, step, name="allreduce.b0"):
    return {"name": name, "cat": "collective", "ph": "X", "ts": ts,
            "dur": dur, "pid": rank, "tid": "grad.w", "step": step}


def _fixture():
    """Two ranks, three cycles.  Aligned-clock story (us, rank0 frame):

      rank0: CYCLE_1@1000  coll[1200..1900]   CYCLE_2@2400
             coll[2500..3100]                 CYCLE_3@3500
      rank1: CYCLE_1@1000  coll[1600..2350]   CYCLE_2@2800
             coll[2500..3100]                 CYCLE_3@3500

    Collectives are stamped with the COMPLETED cycle count at issue
    (step n-1 for a step-n collective), so both carry step=1 / step=2.
    Rank 1's raw timestamps are all shifted by +OFFSET_US.
    """
    r0 = [
        _cycle(1, 1000.0, 0),
        _coll(1200.0, 700.0, 0, step=1),
        _cycle(2, 2400.0, 0),
        _coll(2500.0, 600.0, 0, step=2),
        _cycle(3, 3500.0, 0),
    ]
    r1 = [
        _cycle(1, 1000.0 + OFFSET_US, 1),
        _coll(1600.0 + OFFSET_US, 750.0, 1, step=1),
        _cycle(2, 2800.0 + OFFSET_US, 1),
        _coll(2500.0 + OFFSET_US, 600.0, 1, step=2),
        _cycle(3, 3500.0 + OFFSET_US, 1),
    ]
    return {0: r0, 1: r1}


# ---------------------------------------------------------------------------
# Clock alignment
# ---------------------------------------------------------------------------

def test_clock_offsets_median_recovers_skewed_clock():
    # Per-cycle deltas are 500000 / 500400 / 500000 us; the median kills
    # the one skewed step, recovering the true offset exactly.
    assert clock_offsets(_fixture()) == {0: 0.0, 1: OFFSET_US}


def test_clock_offsets_wall_mode_trusts_raw_clocks():
    assert clock_offsets(_fixture(), align="wall") == {0: 0.0, 1: 0.0}


# ---------------------------------------------------------------------------
# Attribution (hand-computed expectations)
# ---------------------------------------------------------------------------

def test_analyze_per_step_attribution():
    report = analyze(_fixture(), align="cycle")
    assert report["clock_offsets_us"] == {"0": 0.0, "1": OFFSET_US}
    by_step = {s["step"]: s for s in report["steps"]}
    assert sorted(by_step) == [1, 2, 3]

    # Step 1: both ranks arrive together; no step-0 marker, so no
    # critical path; its collectives belong to step 2's window.
    s1 = by_step[1]
    assert s1["skew_ms"] == 0.0
    assert s1["straggler_rank"] is None
    assert s1["critical_path_ms"] is None
    assert s1["buckets"] == []

    # Step 2: rank 1 is 0.4 ms late to the barrier (2800 vs 2400) and
    # 0.4 ms late into the collective (1600 vs 1200).
    s2 = by_step[2]
    assert s2["skew_ms"] == 0.4
    assert s2["straggler_rank"] == 1
    assert s2["critical_path_ms"] == 1.8   # 2800 - 1000
    assert s2["wait_ms"] == 0.4            # 1600 - 1200
    assert s2["wire_ms"] == 0.75           # 2350 - 1600
    assert s2["compute_ms"] == 0.65        # 1.8 - 0.4 - 0.75
    (b,) = s2["buckets"]
    assert b["name"] == "allreduce.b0" and b["tid"] == "grad.w"
    assert b["ranks"] == 2 and b["blamed_rank"] == 1
    assert b["wait_ms"] == 0.4 and b["wire_ms"] == 0.75

    # Step 3: perfectly converged step.
    s3 = by_step[3]
    assert s3["skew_ms"] == 0.0
    assert s3["straggler_rank"] is None
    assert s3["critical_path_ms"] == 1.1   # 3500 - 2400
    assert s3["wait_ms"] == 0.0
    assert s3["wire_ms"] == 0.6            # 3100 - 2500
    assert s3["compute_ms"] == 0.5

    summary = report["summary"]
    assert summary["ranks"] == [0, 1]
    assert summary["steps_analyzed"] == 3
    assert summary["step_skew_ms_median"] == 0.0
    assert summary["step_skew_ms_max"] == 0.4
    assert summary["critical_path_ms_median"] == 1.45
    assert summary["straggler_rank"] == 1
    # cp total 2.9 ms, wait total 0.4, wire total 1.35.
    assert summary["skew_share"] == pytest.approx(0.4 / 2.9, abs=1e-4)
    assert summary["wire_share"] == pytest.approx(1.35 / 2.9, abs=1e-4)
    assert summary["collective_share_measured"] == pytest.approx(
        1.75 / 2.9, abs=1e-4)


def test_analyze_wall_alignment_sees_the_clock_skew():
    # Without barrier alignment the 500 ms clock offset masquerades as
    # per-step skew — the reason `cycle` is the default.
    report = analyze(_fixture(), align="wall")
    assert report["summary"]["step_skew_ms_max"] >= OFFSET_US / 1e3


def test_analyze_single_rank_degrades_gracefully():
    traces = {0: _fixture()[0]}
    report = analyze(traces)
    s2 = next(s for s in report["steps"] if s["step"] == 2)
    assert s2["skew_ms"] == 0.0
    # One-rank collectives: no wait attribution, duration counts as wire.
    assert s2["wait_ms"] == 0.0 and s2["wire_ms"] == 0.7
    assert s2["buckets"][0]["blamed_rank"] is None
    assert report["summary"]["straggler_rank"] == -1


# ---------------------------------------------------------------------------
# Merge: one Perfetto trace, flow events, metadata
# ---------------------------------------------------------------------------

def test_merge_aligns_and_links_ranks():
    merged = merge(_fixture(), align="cycle", flow=True)
    md = merged["metadata"]
    assert md["ranks"] == [0, 1]
    assert md["align"] == "cycle"
    assert md["clock_offsets_us"] == {"0": 0.0, "1": OFFSET_US}

    events = merged["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    assert {(e["name"], e["pid"]) for e in meta} >= {
        ("process_name", 0), ("process_name", 1)}

    # Rank 1's events land on rank 0's clock after alignment.
    r1_cycles = {e["name"]: e["ts"] for e in events
                 if e["ph"] == "i" and e["pid"] == 1}
    assert r1_cycles["CYCLE_1"] == 1000.0
    assert r1_cycles["CYCLE_2"] == 2800.0

    # Five cross-rank groups (3 cycles + 2 stepped collectives), each an
    # s->f pair binding both ranks.
    starts = [e for e in events if e["ph"] == "s"]
    finishes = [e for e in events if e["ph"] == "f"]
    assert len(starts) == len(finishes) == 5 == md["flow_events"] // 2
    assert all(e["cat"] == "xrank" for e in starts + finishes)
    assert all(e.get("bp") == "e" for e in finishes)
    assert sorted(e["id"] for e in starts) == sorted(
        e["id"] for e in finishes)
    # The step-2 collective flow starts at the first-arriving rank (0)
    # and finishes at the straggler (1), bound mid-slice.
    coll_flows = sorted((e for e in starts + finishes
                         if "allreduce.b0" in e["name"] and e["pid"] == 1),
                        key=lambda e: e["ts"])
    assert coll_flows[0]["ph"] == "f"


def test_merge_without_flow_events():
    merged = merge(_fixture(), align="cycle", flow=False)
    assert merged["metadata"]["flow_events"] == 0
    assert not [e for e in merged["traceEvents"] if e["ph"] in "stf"]


def test_merged_file_is_valid_perfetto_json(tmp_path):
    out = tmp_path / "fleet_trace.json"
    write_merged(merge(_fixture(), align="cycle", flow=True), str(out))
    doc = json.loads(out.read_text())
    assert isinstance(doc["traceEvents"], list)
    assert doc["metadata"]["ranks"] == [0, 1]


# ---------------------------------------------------------------------------
# Loaders
# ---------------------------------------------------------------------------

def _write_rank_files(tmp_path, traces=None):
    paths = []
    for r, events in sorted((traces or _fixture()).items()):
        p = tmp_path / f"tl.rank{r}.json"
        p.write_text(json.dumps(events))
        paths.append(str(p))
    return paths


def test_load_events_tolerates_truncated_writer_output(tmp_path):
    # The writer's crash-safe array format: no closing bracket, trailing
    # comma (chrome://tracing accepts it; so must we).
    p = tmp_path / "t.rank0.json"
    body = json.dumps(_fixture()[0])[1:-1]
    p.write_text("[" + body + ",")
    assert len(load_events(str(p))) == len(_fixture()[0])


def test_load_events_accepts_object_form(tmp_path):
    p = tmp_path / "t.json"
    p.write_text(json.dumps({"traceEvents": _fixture()[0]}))
    assert len(load_events(str(p))) == len(_fixture()[0])


def test_rank_falls_back_to_filename(tmp_path):
    p = tmp_path / "t.rank3.json"
    p.write_text(json.dumps([{"name": "CYCLE_1", "ph": "i", "ts": 1.0}]))
    assert sorted(load_rank_traces([str(p)])) == [3]


def test_duplicate_rank_concatenates(tmp_path):
    """Several files carrying the same pid merge into one lane — a
    respawned serving replica's incarnations (`.rank<k>` plus
    `.rank<k>.respawn<j>`) must land on the same replica row."""
    paths = _write_rank_files(tmp_path)
    solo = load_rank_traces([paths[0]])
    both = load_rank_traces([paths[0], paths[0]])
    assert sorted(both) == sorted(solo)
    for rank, events in solo.items():
        assert len(both[rank]) == 2 * len(events)


# ---------------------------------------------------------------------------
# CLI (python -m horovod_tpu.trace)
# ---------------------------------------------------------------------------

def test_cli_merge_and_analyze(tmp_path, capsys):
    paths = _write_rank_files(tmp_path)
    out = tmp_path / "fleet_trace.json"
    assert trace_cli(["merge", *paths, "-o", str(out)]) == 0
    assert "ranks [0, 1]" in capsys.readouterr().out
    doc = json.loads(out.read_text())
    assert doc["metadata"]["flow_events"] == 10

    rep_path = tmp_path / "report.json"
    assert trace_cli(["analyze", *paths, "-o", str(rep_path)]) == 0
    printed = json.loads(capsys.readouterr().out)
    assert printed == json.loads(rep_path.read_text())
    assert printed["summary"]["straggler_rank"] == 1


# ---------------------------------------------------------------------------
# TraceMeasurements: report -> metrics / autotune
# ---------------------------------------------------------------------------

def test_trace_measurements_from_report():
    tm = TraceMeasurements.from_report(analyze(_fixture()))
    assert tm.critical_path_ms == 1.45
    assert tm.step_skew_ms == 0.0          # median over [0, 0.4, 0]
    assert tm.straggler_rank == 1
    assert tm.collective_share_measured == pytest.approx(1.75 / 2.9,
                                                         abs=1e-4)
    # Per-bucket wait+wire: 1.15 ms (step 2) and 0.6 ms (step 3).
    assert tm.bucket_ms == {"allreduce.b0/grad.w": 0.875}


def test_trace_measurements_apply_to_metrics():
    tm = TraceMeasurements.from_report(analyze(_fixture()))
    met_catalog.set_enabled(True)
    try:
        assert tm.apply_to_metrics()
    finally:
        pass
    assert met_catalog.critical_path_ms.labels().get() == 1.45
    assert met_catalog.step_skew_ms.labels().get() == 0.0
    assert met_catalog.straggler_rank.labels().get() == 1

    met_catalog.set_enabled(False)
    try:
        assert not tm.apply_to_metrics()
    finally:
        met_catalog.set_enabled(True)


def test_trace_measurements_feed_autotune():
    class FakePM:
        def record_trace(self, step_ms, items_per_step=1.0, bucket_ms=None):
            self.call = (step_ms, items_per_step, bucket_ms)

    tm = TraceMeasurements.from_report(analyze(_fixture()))
    pm = FakePM()
    assert tm.feed_autotune(pm=pm, items_per_step=32.0)
    assert pm.call == (1.45, 32.0, {"allreduce.b0/grad.w": 0.875})
    # Nothing to feed -> refuse rather than inject a zero-rate sample.
    assert not TraceMeasurements().feed_autotune(pm=pm)


def test_autotune_record_trace_converts_to_rate(tmp_path):
    from horovod_tpu.utils.autotune import ParameterManager
    log = tmp_path / "autotune.csv"
    pm = ParameterManager(warmup_samples=0, log_file=str(log))
    pm.register("fusion_threshold", 1 << 20, 256 << 20, log_scale=True)
    pm.record_trace(2.0, items_per_step=4.0,
                    bucket_ms={"b/t": 0.5, "a/t": 0.25})
    text = log.read_text()
    # 4 items / 2 ms -> 2000 items/s scored as a regular sample, with
    # the per-bucket timings logged for audit.
    assert ",sample,2000.000," in text
    assert "trace_buckets,a/t=0.250;b/t=0.500" in text
    pm.record_trace(0.0)  # ignored, not a divide-by-zero
    assert log.read_text().count(",sample,") == 1


# ---------------------------------------------------------------------------
# Fleet view rendering of the trace gauges
# ---------------------------------------------------------------------------

def _gauge_sample(value):
    return {"kind": "gauge", "labelnames": [], "samples": [[[], value]]}


def _snap(rank, metrics):
    return {"rank": rank, "ts": time.time(), "metrics": metrics}


def test_render_fleet_trace_section():
    snaps = [
        _snap(0, {"hvd_critical_path_ms": _gauge_sample(1.45),
                  "hvd_step_skew_ms": _gauge_sample(0.4),
                  "hvd_straggler_rank": _gauge_sample(1),
                  "hvd_stall_laggards": _gauge_sample(1)}),
        _snap(1, {"hvd_critical_path_ms": _gauge_sample(1.5)}),
    ]
    text = fleet.render_fleet(snaps)
    assert "step critical path (ms): rank0=1.4  rank1=1.5" in text
    assert "step barrier skew (ms): rank0=0.4" in text
    assert "blamed straggler (rank 0's analysis): rank 1" in text
    assert "stall laggards (last warning): rank0=1" in text


def test_render_fleet_can_blame_rank_zero():
    # A straggler gauge of 0 means "rank 0 is to blame", not "unset" —
    # the skew gauge on the same rank disambiguates.
    snaps = [_snap(1, {"hvd_step_skew_ms": _gauge_sample(0.2),
                       "hvd_straggler_rank": _gauge_sample(0)})]
    assert "blamed straggler (rank 1's analysis): rank 0" in (
        fleet.render_fleet(snaps))


def test_render_fleet_without_trace_gauges_has_no_section():
    snaps = [_snap(0, {"hvd_steps_total": {
        "kind": "counter", "labelnames": [], "samples": [[[], 3]]}})]
    text = fleet.render_fleet(snaps)
    assert "critical path" not in text
    assert "straggler" not in text
