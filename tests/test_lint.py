"""Tier-1 enforcement + unit tests for the hvdlint static-analysis suite
(scripts/hvdlint/, docs/STATIC_ANALYSIS.md).

The suite itself never imports jax or horovod_tpu; these tests drive it
in-process against synthetic fixture projects (tmp_path trees) and run
`scripts/lint_all.py` against the real repo as the drift gate.
"""

import os
import re
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import hvdlint  # noqa: E402
from hvdlint import (  # noqa: E402
    EnvVarRegistry,
    ExceptionDiscipline,
    JitPurity,
    LockDiscipline,
    Project,
    run_all,
)

MINI_CATALOG = '''\
from dataclasses import dataclass
from typing import Optional

@dataclass(frozen=True)
class EnvVar:
    name: str
    default: str
    component: str
    description: str
    doc: str = ""
    dynamic_site: Optional[str] = None

CATALOG = (
    EnvVar("HOROVOD_KNOWN", "0", "test", "a known knob"),
)
PREFIXES = {"HOROVOD_": "forwarding filter"}

def render_markdown():
    return "# Environment variables\\n"
'''


def make_project(tmp_path, files, catalog=None, env_doc=None):
    """Build a throwaway repo tree: {relpath: source} + optional env
    catalog/doc, and return an hvdlint Project over it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    if catalog is not None:
        p = tmp_path / "horovod_tpu" / "common" / "env_catalog.py"
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(catalog)
    if env_doc is not None:
        d = tmp_path / "docs"
        d.mkdir(exist_ok=True)
        (d / "ENV_VARS.md").write_text(env_doc)
    return Project(tmp_path)


def rules(findings):
    return sorted({(f.analyzer, f.rule) for f in findings})


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

def test_unlocked_write_flagged(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._value = 0

            def inc(self):
                with self._lock:
                    self._value += 1

            def set(self, v):
                self._value = v
    """})
    fs = LockDiscipline().run(proj)
    assert [(f.rule, f.line) for f in fs] == [("unlocked-write", 13)]
    assert "Box._value" in fs[0].message


def test_consistently_guarded_class_clean(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._value = 0

            def inc(self):
                with self._lock:
                    self._value += 1

            def _drain_locked(self):
                self._value = 0  # caller-holds-the-lock convention
    """})
    assert LockDiscipline().run(proj) == []


def test_unlocked_write_pragma(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._value = 0

            def inc(self):
                with self._lock:
                    self._value += 1

            def set(self, v):
                # lint: allow-unlocked(single writer thread by contract)
                self._value = v
    """})
    assert LockDiscipline().run(proj) == []


def test_lock_order_inversion(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def fwd():
            with _a:
                with _b:
                    pass

        def rev():
            with _b:
                with _a:
                    pass
    """})
    fs = LockDiscipline().run(proj)
    assert [f.rule for f in fs] == ["order-inversion"]
    assert "_a" in fs[0].message and "_b" in fs[0].message


def test_lock_order_consistent_clean(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def f():
            with _a:
                with _b:
                    pass

        def g():
            with _a:
                with _b:
                    pass
    """})
    assert LockDiscipline().run(proj) == []


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------

def test_impure_traced_decorator(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import time
        import jax

        @jax.jit
        def step(x):
            t0 = time.perf_counter()
            return x + t0
    """})
    fs = JitPurity().run(proj)
    assert [(f.rule, f.line) for f in fs] == [("impure-call", 6)]
    assert "perf_counter" in fs[0].message


def test_impure_fn_passed_to_tracer(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import os
        import jax

        def step(x):
            if os.getenv("HOROVOD_DEBUG"):
                print("tracing", x.shape)
            return x

        fast = jax.jit(step)
    """})
    fs = JitPurity().run(proj)
    assert ("jit-purity", "impure-call") in rules(fs)
    assert {f.line for f in fs} == {5, 6}  # os.getenv + print


def test_partial_jit_and_shard_map_marked(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import logging
        from functools import partial
        import jax
        from jax import shard_map

        logger = logging.getLogger(__name__)

        def inner(x):
            logger.info("traced %s", x)
            return x

        fast = partial(jax.jit, donate_argnums=0)(inner)
        sharded = jax.jit(shard_map(inner, mesh=None))
    """})
    fs = JitPurity().run(proj)
    assert [f.rule for f in fs] == ["impure-call"]
    assert "logging" in fs[0].message


def test_untraced_fn_not_flagged(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import time

        def host_loop(x):
            return time.perf_counter() + x
    """})
    assert JitPurity().run(proj) == []


def test_plain_outer_call_arg_not_traced(tmp_path):
    # jax.jit(f)(x): `x` is a runtime argument, not a traced callable.
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import time
        import jax

        def pure(x):
            return x * 2

        def measure(x):
            return time.monotonic()

        y = jax.jit(pure)(measure(3))
    """})
    assert JitPurity().run(proj) == []


def test_impure_pragma_and_jax_random_ok(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import random
        import jax

        @jax.jit
        def step(key, x):
            n = random.random()  # lint: allow-impure(trace-time seed ok)
            return x + jax.random.uniform(key) + n
    """})
    assert JitPurity().run(proj) == []


def test_nonlocal_mutation_flagged(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import jax

        _count = 0

        @jax.jit
        def step(x):
            global _count
            _count += 1
            return x
    """})
    fs = JitPurity().run(proj)
    assert [f.rule for f in fs] == ["nonlocal-mutation"]


def test_metrics_in_traced_body_flagged(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import jax
        from .metrics import catalog as _met

        @jax.jit
        def step(x):
            _met.collective_calls.labels("allreduce").inc()
            return x
    """})
    fs = JitPurity().run(proj)
    # both the .labels(...) and the .inc() stages of the chain count
    assert {(f.rule, f.line) for f in fs} == {("impure-call", 6)}
    assert any("metrics recording" in f.message for f in fs)


# ---------------------------------------------------------------------------
# env-registry
# ---------------------------------------------------------------------------

def test_unknown_env_literal_and_helper(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import os
        from .common import util

        a = os.environ.get("HOROVOD_MYSTERY")
        b = util.env_bool("ALSO_MYSTERY")
        c = util.env_int("KNOWN", 3)
    """}, catalog=MINI_CATALOG, env_doc="# Environment variables\n")
    fs = EnvVarRegistry().run(proj)
    unknown = sorted((f for f in fs if f.rule == "unknown-env"),
                     key=lambda f: f.line)
    assert [f.line for f in unknown] == [4, 5]
    assert "HOROVOD_MYSTERY" in unknown[0].message
    assert "HOROVOD_ALSO_MYSTERY" in unknown[1].message


def test_dead_entry_and_stale_docs(tmp_path):
    proj = make_project(
        tmp_path, {"horovod_tpu/m.py": "x = 1\n"},
        catalog=MINI_CATALOG, env_doc="out of date\n")
    got = {f.rule for f in EnvVarRegistry().run(proj)}
    assert got == {"dead-entry", "stale-docs"}


def test_dynamic_env_requires_registration(tmp_path):
    src = """\
        from .common import util

        def read(site):
            return util.env_float(f"{site}_RETRY_JITTER", 0.1)
    """
    proj = make_project(tmp_path, {"horovod_tpu/m.py": src},
                        catalog=MINI_CATALOG,
                        env_doc="# Environment variables\n")
    fs = EnvVarRegistry().run(proj)
    assert ("env-registry", "dynamic-env") in rules(fs)

    cat = MINI_CATALOG.replace(
        '"a known knob"),',
        '"a known knob", "", "horovod_tpu/m.py"),')
    src_ok = textwrap.dedent(src) + '\nx = util.getenv("KNOWN")\n'
    proj2 = make_project(tmp_path / "ok", {"horovod_tpu/m.py": src_ok},
                         catalog=cat, env_doc="# Environment variables\n")
    assert [f.rule for f in EnvVarRegistry().run(proj2)] == []


def test_unknown_prefix_literal(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        from .common import util

        FWD = [k for k in ("a",) if k.startswith("HOROVOD_SECRET_")]
        x = util.getenv("KNOWN")
    """}, catalog=MINI_CATALOG, env_doc="# Environment variables\n")
    fs = EnvVarRegistry().run(proj)
    assert [f.rule for f in fs] == ["unknown-prefix"]


def test_missing_catalog(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": "x = 1\n"})
    fs = EnvVarRegistry().run(proj)
    assert [f.rule for f in fs] == ["missing-catalog"]


def test_repo_env_docs_fresh():
    """docs/ENV_VARS.md must byte-match the catalog's renderer."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "gen_env_docs.py"),
         REPO, "--check"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# exception-discipline
# ---------------------------------------------------------------------------

def test_bare_assert_flagged_and_pragma(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        def f(x):
            assert x > 0
            # lint: allow-assert(shape contract checked by caller)
            assert x < 10
            return x
    """})
    fs = ExceptionDiscipline().run(proj)
    assert [(f.rule, f.line) for f in fs] == [("bare-assert", 2)]


def test_silent_swallow_flagged(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        def f():
            try:
                risky()
            except Exception:
                pass
    """})
    fs = ExceptionDiscipline().run(proj)
    assert [(f.rule, f.line) for f in fs] == [("silent-swallow", 4)]


def test_swallow_pragma_and_logged_handler_clean(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        import logging

        def f():
            try:
                risky()
            # lint: allow-swallow(best-effort cleanup at shutdown)
            except Exception:
                pass
            try:
                risky()
            except Exception as e:
                logging.debug("risky failed: %s", e)
            try:
                risky()
            except ValueError:
                pass
    """})
    assert ExceptionDiscipline().run(proj) == []


def test_pragma_without_reason_is_a_finding(tmp_path):
    proj = make_project(tmp_path, {"horovod_tpu/m.py": """\
        def f():
            try:
                risky()
            # lint: allow-swallow()
            except Exception:
                pass
    """})
    fs = run_all(Project(tmp_path), [ExceptionDiscipline()])
    assert rules(fs) == [("exception-discipline", "silent-swallow"),
                        ("pragma", "missing-reason")]


def test_parse_error_reported_once(tmp_path):
    proj = make_project(
        tmp_path, {"horovod_tpu/m.py": "def broken(:\n    pass\n"})
    fs = run_all(proj, [ExceptionDiscipline(), LockDiscipline()])
    assert [(f.analyzer, f.rule) for f in fs] == [("core", "parse-error")]


# ---------------------------------------------------------------------------
# runner / CLI / shims against the real repo
# ---------------------------------------------------------------------------

def test_lint_all_repo_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint_all.py"),
         REPO],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "analyzer(s) clean" in proc.stdout


def test_lint_all_github_format(tmp_path):
    make_project(tmp_path, {"horovod_tpu/m.py": """\
        def f(x):
            assert x
    """})
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint_all.py"),
         str(tmp_path), "--format=github",
         "--only=exception-discipline"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert proc.stdout.startswith(
        "::error file=horovod_tpu/m.py,line=2,"
        "title=exception-discipline/bare-assert::")


def test_lint_all_unknown_analyzer():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint_all.py"),
         REPO, "--only=nope"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "unknown analyzer" in proc.stderr


def test_lint_all_list():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint_all.py"),
         "--list"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    for a in hvdlint.ALL:
        assert a.name in proc.stdout


def test_no_jax_import_in_lint_machinery():
    """The whole suite must run on a machine without jax."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, 'scripts'); "
         "sys.modules['jax'] = None; "  # any `import jax` now explodes
         "import lint_all; sys.exit(lint_all.main(['.']))"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# sharded-optimizer catalog coverage (r7 gauges + ag_fusion knob)
# ---------------------------------------------------------------------------

from hvdlint.catalogs import (  # noqa: E402
    MetricsCatalog,
    _DOC_ROW_RE,
    _KNOB_RE,
    _REG_RE,
)

SHARDED_GAUGES = ("hvd_opt_state_bytes", "hvd_rs_bytes",
                  "hvd_param_ag_bytes")


def _repo_text(rel):
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def test_sharded_gauges_registered_and_documented():
    """The three ZeRO-1 gauges must exist on BOTH sides the analyzer
    diffs — registered in the catalog and rowed in docs/METRICS.md —
    so deleting either side is a tier-1 failure, not silent drift."""
    declared = set(_REG_RE.findall(
        _repo_text("horovod_tpu/metrics/catalog.py")))
    documented = set(_DOC_ROW_RE.findall(_repo_text("docs/METRICS.md")))
    for gauge in SHARDED_GAUGES:
        assert gauge in declared, gauge
        assert gauge in documented, gauge


def test_ag_fusion_knob_registered_and_documented():
    knobs = set(_KNOB_RE.findall(
        _repo_text("horovod_tpu/utils/autotune.py")))
    assert "ag_fusion" in knobs
    assert "`ag_fusion`" in _repo_text("docs/AUTOTUNE.md")


def test_metrics_catalog_catches_sharded_gauge_doc_drift(tmp_path):
    """Drop one sharded gauge's doc row from a copy of the REAL repo
    files: the metrics-catalog analyzer must flag exactly that gauge."""
    doc = "\n".join(
        line for line in _repo_text("docs/METRICS.md").splitlines()
        if "`hvd_rs_bytes`" not in line)
    proj = make_project(tmp_path, {
        "horovod_tpu/metrics/catalog.py":
            _repo_text("horovod_tpu/metrics/catalog.py"),
        "horovod_tpu/utils/autotune.py":
            _repo_text("horovod_tpu/utils/autotune.py"),
        "docs/METRICS.md": doc,
        "docs/AUTOTUNE.md": _repo_text("docs/AUTOTUNE.md"),
    })
    findings = MetricsCatalog().run(proj)
    assert [(f.rule, "hvd_rs_bytes" in f.message) for f in findings] == [
        ("undocumented-metric", True)]


def test_anomaly_catalog_clean_on_repo():
    """Detector kinds in metrics/anomaly.py and the TELEMETRY.md
    detector table must agree on the real tree."""
    from hvdlint import AnomalyCatalog
    assert AnomalyCatalog().run(Project(REPO)) == []


def test_anomaly_catalog_catches_undocumented_detector(tmp_path):
    """A new detector class with no TELEMETRY.md row must be flagged."""
    from hvdlint import AnomalyCatalog
    src = _repo_text("horovod_tpu/metrics/anomaly.py") + (
        "\n\nclass MadDetector:\n    kind = \"mad_outlier\"\n")
    proj = make_project(tmp_path, {
        "horovod_tpu/metrics/anomaly.py": src,
        "docs/TELEMETRY.md": _repo_text("docs/TELEMETRY.md"),
    })
    findings = AnomalyCatalog().run(proj)
    assert [(f.rule, "mad_outlier" in f.message) for f in findings] == [
        ("undocumented-detector", True)]


def test_anomaly_catalog_catches_stale_doc_row(tmp_path):
    """A detector-catalog row whose class is gone must be flagged."""
    from hvdlint import AnomalyCatalog
    doc = _repo_text("docs/TELEMETRY.md").replace(
        "<!-- detector-catalog:end -->",
        "| `ghost_detector` | nothing | never |\n"
        "<!-- detector-catalog:end -->")
    proj = make_project(tmp_path, {
        "horovod_tpu/metrics/anomaly.py":
            _repo_text("horovod_tpu/metrics/anomaly.py"),
        "docs/TELEMETRY.md": doc,
    })
    findings = AnomalyCatalog().run(proj)
    assert [(f.rule, "ghost_detector" in f.message) for f in findings] \
        == [("stale-doc-entry", True)]


def test_metrics_catalog_catches_ag_fusion_knob_drift(tmp_path):
    """Strip the `ag_fusion` mention from a copy of docs/AUTOTUNE.md:
    the analyzer must report the knob as undocumented."""
    at_doc = _repo_text("docs/AUTOTUNE.md").replace("`ag_fusion`",
                                                    "(redacted)")
    proj = make_project(tmp_path, {
        "horovod_tpu/metrics/catalog.py":
            _repo_text("horovod_tpu/metrics/catalog.py"),
        "horovod_tpu/utils/autotune.py":
            _repo_text("horovod_tpu/utils/autotune.py"),
        "docs/METRICS.md": _repo_text("docs/METRICS.md"),
        "docs/AUTOTUNE.md": at_doc,
    })
    findings = MetricsCatalog().run(proj)
    assert [(f.rule, "ag_fusion" in f.message) for f in findings] == [
        ("undocumented-knob", True)]


# ---------------------------------------------------------------------------
# wire-registry (r6, scripts/hvdlint/wires.py)
# ---------------------------------------------------------------------------

from hvdlint import WireRegistry  # noqa: E402

WIRE_METRICS = ("hvd_wire_bytes_saved", "hvd_wire_bytes_saved_per_step",
                "hvd_wire_format_bytes")


def test_wire_metrics_registered_and_documented():
    declared = set(_REG_RE.findall(
        _repo_text("horovod_tpu/metrics/catalog.py")))
    documented = set(_DOC_ROW_RE.findall(_repo_text("docs/METRICS.md")))
    for metric in WIRE_METRICS:
        assert metric in declared, metric
        assert metric in documented, metric


def test_wire_threshold_knob_registered_and_documented():
    knobs = set(_KNOB_RE.findall(
        _repo_text("horovod_tpu/utils/autotune.py")))
    assert "wire_threshold" in knobs
    assert "`wire_threshold`" in _repo_text("docs/AUTOTUNE.md")


def _wire_project(tmp_path, overrides=None):
    """Copy the real wire module + doc into a fixture tree, with
    optional per-file overrides."""
    files = {
        "horovod_tpu/ops/wire.py": _repo_text("horovod_tpu/ops/wire.py"),
        "docs/WIRE.md": _repo_text("docs/WIRE.md"),
    }
    files.update(overrides or {})
    return make_project(tmp_path, files)


def test_wire_registry_repo_clean():
    assert WireRegistry().run(Project(REPO)) == []


def test_unknown_wire_literal_flagged(tmp_path):
    proj = _wire_project(tmp_path, {
        "horovod_tpu/parallel/bad.py": '''\
            def f(x):
                return reduce(x, wire="int9")
            ''',
    })
    findings = WireRegistry().run(proj)
    assert [(f.rule, "int9" in f.message) for f in findings] == [
        ("unknown-wire", True)]


def test_known_wire_forms_clean(tmp_path):
    proj = _wire_project(tmp_path, {
        "horovod_tpu/parallel/ok.py": '''\
            class C:
                wire = "fp16"

            def f(x, dcn_wire="int4", allgather_wire: str = "bf16"):
                codec = get_codec("fp8_e4m3")
                return reduce(x, wire="int8")
            ''',
    })
    assert WireRegistry().run(proj) == []


def test_wire_doc_drift_both_directions(tmp_path):
    # Drop a codec's doc row -> undocumented-codec.
    doc = "\n".join(
        line for line in _repo_text("docs/WIRE.md").splitlines()
        if not line.startswith("| `int4`"))
    proj = _wire_project(tmp_path, {"docs/WIRE.md": doc})
    findings = WireRegistry().run(proj)
    assert [(f.rule, "int4" in f.message) for f in findings] == [
        ("undocumented-codec", True)]
    # Remove the registration but keep the row -> stale-doc-entry.
    src = _repo_text("horovod_tpu/ops/wire.py").replace(
        'name="int4"', 'name="int8"')
    proj2 = _wire_project(tmp_path, {"horovod_tpu/ops/wire.py": src})
    findings2 = WireRegistry().run(proj2)
    assert ("stale-doc-entry", True) in [
        (f.rule, "int4" in f.message) for f in findings2]


def test_wire_registry_missing_doc_is_error(tmp_path):
    files = {
        "horovod_tpu/ops/wire.py": _repo_text("horovod_tpu/ops/wire.py"),
    }
    proj = make_project(tmp_path, files)
    findings = WireRegistry().run(proj)
    assert [f.rule for f in findings] == ["error"]
    assert "docs/WIRE.md" in findings[0].message


# ---------------------------------------------------------------------------
# training-health guardian (guard/) catalog gates
# ---------------------------------------------------------------------------

from hvdlint import FaultPoints  # noqa: E402
from hvdlint.catalogs import (  # noqa: E402
    _CAT_RE,
    _FAULT_DOC_ROW_RE,
    _SITE_RE,
)

GUARD_METRICS = ("hvd_nonfinite_steps_total", "hvd_loss_scale",
                 "hvd_guard_rollbacks_total", "hvd_digest_mismatch_total")
GUARD_KNOBS = ("loss_scale_growth_interval", "guard_digest_interval")
GUARD_FAULT_POINTS = ("guard.nan_grad", "guard.param_bitflip")
GUARD_ENV_VARS = ("HOROVOD_GUARD", "HOROVOD_GUARD_LOSS_SCALE",
                  "HOROVOD_GUARD_GROWTH_INTERVAL",
                  "HOROVOD_GUARD_DIGEST_INTERVAL",
                  "HOROVOD_GUARD_MAX_NONFINITE",
                  "HOROVOD_CONSISTENCY_TIMEOUT",
                  "HOROVOD_CKPT_QUARANTINE_KEEP")

_ENV_DECL_RE = re.compile(r'_v\(\s*"(HOROVOD_[A-Z0-9_]+)"')
_ENV_DOC_ROW_RE = re.compile(r"^\|\s*`(HOROVOD_[A-Z0-9_]+)`",
                             re.MULTILINE)


def test_guard_metrics_registered_and_documented():
    """The four guardian metrics must exist on BOTH sides the
    metrics-catalog analyzer diffs, so deleting either side is a tier-1
    failure, not silent drift."""
    declared = set(_REG_RE.findall(
        _repo_text("horovod_tpu/metrics/catalog.py")))
    documented = set(_DOC_ROW_RE.findall(_repo_text("docs/METRICS.md")))
    for metric in GUARD_METRICS:
        assert metric in declared, metric
        assert metric in documented, metric


def test_guard_knobs_registered_and_documented():
    knobs = set(_KNOB_RE.findall(
        _repo_text("horovod_tpu/utils/autotune.py")))
    doc = _repo_text("docs/AUTOTUNE.md")
    for knob in GUARD_KNOBS:
        assert knob in knobs, knob
        assert f"`{knob}`" in doc, knob


def test_guard_fault_points_declared_fired_documented():
    declared = set(_CAT_RE.findall(
        _repo_text("horovod_tpu/faults/__init__.py")))
    documented = set(_FAULT_DOC_ROW_RE.findall(
        _repo_text("docs/FAULT_TOLERANCE.md")))
    fired = set(_SITE_RE.findall(
        _repo_text("horovod_tpu/guard/controller.py")))
    for point in GUARD_FAULT_POINTS:
        assert point in declared, point
        assert point in documented, point
        assert point in fired, point


def test_guard_env_vars_cataloged_and_documented():
    declared = set(_ENV_DECL_RE.findall(
        _repo_text("horovod_tpu/common/env_catalog.py")))
    documented = set(_ENV_DOC_ROW_RE.findall(
        _repo_text("docs/ENV_VARS.md")))
    for var in GUARD_ENV_VARS:
        assert var in declared, var
        assert var in documented, var


def test_fault_points_catches_guard_doc_drift(tmp_path):
    """Drop guard.nan_grad's doc row from a copy of the REAL repo
    files: the fault-points analyzer must flag exactly that point."""
    doc = "\n".join(
        line for line in
        _repo_text("docs/FAULT_TOLERANCE.md").splitlines()
        if "`guard.nan_grad`" not in line)
    proj = make_project(tmp_path, {
        "horovod_tpu/faults/__init__.py":
            _repo_text("horovod_tpu/faults/__init__.py"),
        "docs/FAULT_TOLERANCE.md": doc,
    })
    findings = FaultPoints().run(proj)
    # The fixture carries no call sites, so ignore the dead-point noise
    # and check the doc-drift rule precisely.
    assert [(f.rule, "guard.nan_grad" in f.message) for f in findings
            if f.rule == "undocumented-point"] == [
        ("undocumented-point", True)]


# ---------------------------------------------------------------------------
# pallas-guard
# ---------------------------------------------------------------------------

def test_pallas_call_without_interpret_flagged(tmp_path):
    from hvdlint import PallasGuard
    proj = make_project(tmp_path, {"horovod_tpu/k.py": """\
        import jax
        from jax.experimental import pallas as pl  # noqa

        def kern(x):
            return pl.pallas_call(lambda r, o: None,
                                  out_shape=x)(x)
        """})
    got = rules(PallasGuard().run(proj))
    assert ("pallas-guard", "missing-interpret") in got
    # the bare module-level pallas import is also unconditional
    assert ("pallas-guard", "unguarded-import") in got


def test_pallas_static_interpret_flagged(tmp_path):
    from hvdlint import PallasGuard
    proj = make_project(tmp_path, {"horovod_tpu/k.py": """\
        try:
            from jax.experimental import pallas as pl
        except ImportError:
            pl = None

        def kern(x):
            return pl.pallas_call(lambda r, o: None, out_shape=x,
                                  interpret=True)(x)
        """})
    got = rules(PallasGuard().run(proj))
    assert got == [("pallas-guard", "static-interpret")]


def test_pallas_runtime_guard_clean(tmp_path):
    from hvdlint import PallasGuard
    proj = make_project(tmp_path, {"horovod_tpu/k.py": """\
        PALLAS_AVAILABLE = True
        if PALLAS_AVAILABLE:
            from jax.experimental import pallas as pl

        def _interpret():
            return False

        def kern(x):
            return pl.pallas_call(lambda r, o: None, out_shape=x,
                                  interpret=_interpret())(x)
        """})
    assert PallasGuard().run(proj) == []


def test_pallas_guard_pragma_suppresses(tmp_path):
    from hvdlint import PallasGuard
    proj = make_project(tmp_path, {"horovod_tpu/k.py": """\
        try:
            from jax.experimental import pallas as pl
        except ImportError:
            pl = None

        def kern(x):
            # lint: allow-static-interpret(debug-only helper)
            return pl.pallas_call(lambda r, o: None, out_shape=x,
                                  interpret=True)(x)
        """})
    assert PallasGuard().run(proj) == []


# ---------------------------------------------------------------------------
# timeline-catalog (fleet tracer, scripts/hvdlint/timeline_cat.py)
# ---------------------------------------------------------------------------

from hvdlint import TimelineCatalog  # noqa: E402

TRACE_INSTANT_ROWS = ("CYCLE_n", "guard_bucket_k", "wire_bucket_k",
                      "fused_bucket_k", "PROFILER_TRACE_START",
                      "serve_submit", "serve_first_token", "serve_evict",
                      "slo_toggle")
SERVE_SPAN_ROWS = ("step", "queue_wait", "prefill", "decode")


def _timeline_doc(rows, span_rows=None):
    table = "\n".join(f"| `{r}` | somewhere | something |" for r in rows)
    doc = ("# Timeline\n\n<!-- instant-catalog:start -->\n"
           "| Instant | Emitted by | Meaning |\n|---|---|---|\n"
           f"{table}\n<!-- instant-catalog:end -->\n")
    if span_rows is not None:
        spans = "\n".join(f"| `{r}` | somewhere | something |"
                          for r in span_rows)
        doc += ("\n<!-- span-catalog:start -->\n"
                "| Span | Emitted by | Meaning |\n|---|---|---|\n"
                f"{spans}\n<!-- span-catalog:end -->\n")
    return doc


def test_timeline_catalog_clean_fixture(tmp_path):
    proj = make_project(tmp_path, {
        "horovod_tpu/a.py": '''\
            MARKER = "PROFILER_TRACE_START"

            def f(tl, k):
                tl.instant(f"wire_bucket_{k}", category="wire")
                tl.instant(MARKER, category="profiler")
            ''',
        "docs/TIMELINE.md": _timeline_doc(
            ("wire_bucket_k", "PROFILER_TRACE_START")),
    })
    assert TimelineCatalog().run(proj) == []


def test_timeline_catalog_undocumented_instant(tmp_path):
    proj = make_project(tmp_path, {
        "horovod_tpu/a.py": '''\
            def f(tl, n):
                tl.instant(f"CYCLE_{n}", category="cycle")
                tl.instant("surprise_marker", category="event")
            ''',
        "docs/TIMELINE.md": _timeline_doc(("CYCLE_n",)),
    })
    findings = TimelineCatalog().run(proj)
    assert [(f.rule, "surprise_marker" in f.message) for f in findings] \
        == [("undocumented-instant", True)]
    assert findings[0].path == "horovod_tpu/a.py"


def test_timeline_catalog_stale_doc_entry(tmp_path):
    proj = make_project(tmp_path, {
        "horovod_tpu/a.py": '''\
            def f(tl, n):
                tl.instant(f"CYCLE_{n}", category="cycle")
            ''',
        "docs/TIMELINE.md": _timeline_doc(("CYCLE_n", "ghost_marker")),
    })
    findings = TimelineCatalog().run(proj)
    assert [(f.rule, "ghost_marker" in f.message) for f in findings] \
        == [("stale-doc-entry", True)]
    assert findings[0].path == "docs/TIMELINE.md"


def test_timeline_catalog_missing_section_is_error(tmp_path):
    proj = make_project(tmp_path, {
        "horovod_tpu/a.py": '''\
            def f(tl):
                tl.instant("evt")
            ''',
        "docs/TIMELINE.md": "# Timeline\n\nno catalog table here\n",
    })
    findings = TimelineCatalog().run(proj)
    assert [f.rule for f in findings] == ["error"]
    assert "instant-catalog" in findings[0].message


def test_timeline_catalog_span_drift_both_directions(tmp_path):
    """The span catalog is linted like the instant catalog: an emitted
    `.complete()` name with no row, and a rowed span emitted nowhere,
    are both findings."""
    proj = make_project(tmp_path, {
        "horovod_tpu/a.py": '''\
            def f(tl, t0):
                tl.complete("queue_wait", category="serve", start_us=t0)
                tl.complete("mystery_span", category="serve", start_us=t0)
                tl.instant("evt", category="event")
            ''',
        "docs/TIMELINE.md": _timeline_doc(
            ("evt",), span_rows=("queue_wait", "ghost_span")),
    })
    findings = TimelineCatalog().run(proj)
    assert sorted((f.rule, f.path) for f in findings) == [
        ("stale-doc-entry", "docs/TIMELINE.md"),
        ("undocumented-span", "horovod_tpu/a.py"),
    ]
    assert any("mystery_span" in f.message for f in findings)
    assert any("ghost_span" in f.message for f in findings)


def test_timeline_catalog_spans_need_section_only_when_emitted(tmp_path):
    """No `.complete()` call sites -> no span table required (the
    instant-only fixtures above); emitted spans without a span-catalog
    section -> error."""
    proj = make_project(tmp_path, {
        "horovod_tpu/a.py": '''\
            def f(tl, t0):
                tl.complete("queue_wait", category="serve", start_us=t0)
                tl.instant("evt", category="event")
            ''',
        "docs/TIMELINE.md": _timeline_doc(("evt",)),
    })
    findings = TimelineCatalog().run(proj)
    assert [f.rule for f in findings] == ["error"]
    assert "span-catalog" in findings[0].message


def test_trace_instants_emitted_and_documented():
    """Every fleet-tracer instant family must exist on BOTH sides the
    timeline-catalog analyzer diffs — emitted in the package and rowed
    in docs/TIMELINE.md — so deleting either side is a tier-1 failure."""
    from hvdlint.timeline_cat import _SPAN_SECTION_RE, _doc_rows
    doc = _repo_text("docs/TIMELINE.md")
    rows = set(_doc_rows(doc))
    for name in TRACE_INSTANT_ROWS:
        assert name in rows, name
    spans = set(_doc_rows(doc, _SPAN_SECTION_RE))
    for name in SERVE_SPAN_ROWS:
        assert name in spans, name
    assert TimelineCatalog().run(Project(REPO)) == []


def test_trace_gauges_registered_and_documented():
    """The tracer's continuous surface (docs/TRACE.md) in the metrics
    catalog and docs/METRICS.md, both directions."""
    declared = set(_REG_RE.findall(
        _repo_text("horovod_tpu/metrics/catalog.py")))
    documented = set(_DOC_ROW_RE.findall(_repo_text("docs/METRICS.md")))
    for gauge in ("hvd_critical_path_ms", "hvd_step_skew_ms",
                  "hvd_straggler_rank", "hvd_stall_laggards"):
        assert gauge in declared, gauge
        assert gauge in documented, gauge


def test_trace_env_vars_cataloged_and_documented():
    declared = set(_ENV_DECL_RE.findall(
        _repo_text("horovod_tpu/common/env_catalog.py")))
    documented = set(_ENV_DOC_ROW_RE.findall(
        _repo_text("docs/ENV_VARS.md")))
    for var in ("HOROVOD_TRACE_STEP_SPANS", "HOROVOD_TRACE_ALIGN",
                "HOROVOD_TRACE_FLOW_EVENTS"):
        assert var in declared, var
        assert var in documented, var
