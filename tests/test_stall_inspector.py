"""Stall-inspector two-tier policy driven by a fake clock, plus the
metrics coupling (hvd_stall_* counters; watchdog-as-fleet-publisher).

Complements test_aux.py (which covers warn-once, degraded mode, async
result tracking); here the warn->abort escalation is walked explicitly
through time via check(now=...).
"""

import time

from horovod_tpu.metrics import catalog as met_catalog
from horovod_tpu.utils import stall_inspector as stall_mod


def _make(warn, shutdown):
    warnings, aborts = [], []
    insp = stall_mod.StallInspector(
        warn_time_seconds=warn,
        shutdown_time_seconds=shutdown,
        warn_fn=warnings.append,
        abort_fn=aborts.append,
    )
    return insp, warnings, aborts


def test_two_tier_policy_fake_clock():
    insp, warnings, aborts = _make(warn=10.0, shutdown=30.0)
    t0 = time.time()
    insp.record_start("ALLREDUCE:grad.w")

    # Below the warn threshold: silence.
    assert insp.check(now=t0 + 5) == []
    assert warnings == [] and aborts == []

    # Past warn, below shutdown: exactly one warning, no abort.
    assert insp.check(now=t0 + 15) == ["ALLREDUCE:grad.w"]
    assert len(warnings) == 1 and "ALLREDUCE:grad.w" in warnings[0]
    assert aborts == []

    # Re-checking does not re-warn the same op.
    assert insp.check(now=t0 + 20) == []
    assert len(warnings) == 1

    # Past shutdown: the abort tier fires with the worst op named.
    insp.check(now=t0 + 35)
    assert len(aborts) == 1 and "ALLREDUCE:grad.w" in aborts[0]


def test_shutdown_tier_disabled_by_default():
    insp, warnings, aborts = _make(warn=10.0, shutdown=0.0)
    t0 = time.time()
    insp.record_start("BARRIER")
    insp.check(now=t0 + 1e6)  # absurdly stalled
    assert len(warnings) == 1
    assert aborts == []  # shutdown_time=0 never aborts (reference default)


def test_completed_op_never_warns():
    insp, warnings, aborts = _make(warn=10.0, shutdown=0.0)
    t0 = time.time()
    key = insp.record_start("ALLGATHER:x")
    insp.record_end(key)
    assert insp.check(now=t0 + 100) == []
    assert warnings == []


def test_warning_and_abort_increment_metrics():
    warn_c = met_catalog.stall_warnings
    abort_c = met_catalog.stall_aborts
    w0 = warn_c._solo().get()
    a0 = abort_c._solo().get()

    insp, warnings, aborts = _make(warn=10.0, shutdown=30.0)
    t0 = time.time()
    insp.record_start("ALLREDUCE:g")
    insp.check(now=t0 + 15)
    assert warn_c._solo().get() == w0 + 1
    assert abort_c._solo().get() == a0

    insp.check(now=t0 + 40)
    assert abort_c._solo().get() == a0 + 1


def test_watchdog_publishes_metrics_snapshots():
    """The watchdog thread doubles as the fleet metrics publisher: with a
    reporter attached, metrics/rank/<rank> appears on the KV."""
    from horovod_tpu.metrics import fleet
    from horovod_tpu.runner.rendezvous import (
        RendezvousClient, RendezvousServer)

    srv = RendezvousServer(prefer_native=False)
    port = srv.start(0)
    try:
        client = RendezvousClient("127.0.0.1", port, srv.secret)
        reporter = stall_mod.KvRankReporter(client, rank=5)
        insp = stall_mod.StallInspector(
            warn_time_seconds=60.0, check_interval_seconds=0.05,
            reporter=reporter)
        insp.start()
        try:
            deadline = time.time() + 10
            snaps = []
            while time.time() < deadline and not snaps:
                snaps = fleet.read_fleet(client)
                time.sleep(0.05)
        finally:
            insp.stop()
        assert snaps, "watchdog never published a metrics snapshot"
        assert snaps[0]["rank"] == 5
        assert "metrics" in snaps[0]
        # The stall heartbeat rides the same channel (unchanged behavior).
        assert client.get("stall/rank/5") is not None
    finally:
        srv.stop()
