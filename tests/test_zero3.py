"""ZeRO-3 parameter placement (parallel/zero3.py): shard-at-rest layout,
just-in-time bucket gather in reverse-availability prefetch order, fused
gather+matmul routing, and the loud re-init drift contract.

The optimizer side of stage 3 is stage 2 (tests/test_optimizer.py
TestZero2); this file covers the parameter residency half."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd

N = 8

# Three single-leaf shard groups under fusion_threshold_bytes=64: dict
# flattening is key-sorted (b1, w1, w2), reverse-size bucket traversal
# makes the partition [w2, w1, b1].
PARAMS = {
    "w1": jnp.arange(40, dtype=jnp.float32).reshape(8, 5),
    "b1": jnp.arange(5, dtype=jnp.float32) * 0.5,
    "w2": jnp.arange(16, dtype=jnp.float32).reshape(16, 1) * 2.0,
}


def _placement(**kw):
    base = dict(fusion_threshold_bytes=64)
    base.update(kw)
    return hvd.zero3_placement(PARAMS, **base)


def _gather_jit(pl, rows, specs=None):
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def body(rows):
        t = pl.gather(rows)
        return tuple(t[k] for k in sorted(t))

    sm = shard_map(body, mesh=hvd.global_mesh(),
                   in_specs=(specs if specs is not None else P(),),
                   out_specs=P(), check_vma=False)
    return jax.jit(sm)(rows)


class TestShardGather:
    def test_eager_roundtrip_bitwise(self):
        pl = _placement()
        rows = pl.shard(PARAMS)
        assert all(r.shape == (N, g.shard_sz)
                   for r, g in zip(rows, pl.groups))
        back = pl.gather(rows)
        for k, v in PARAMS.items():
            np.testing.assert_array_equal(np.asarray(back[k]),
                                          np.asarray(v))

    def test_in_jit_gather_bitwise(self):
        pl = _placement()
        rows = pl.shard(PARAMS)
        outs = _gather_jit(pl, rows)
        for k, o in zip(sorted(PARAMS), outs):
            np.testing.assert_array_equal(np.asarray(o),
                                          np.asarray(PARAMS[k]))

    def test_placed_rows_gather_bitwise(self):
        """True sharding: rows placed with specs() hold (1, shard) per
        chip; the in-jit gather reassembles the identical tree."""
        from jax.sharding import NamedSharding

        pl = _placement()
        rows = pl.shard(PARAMS)
        mesh = hvd.global_mesh()
        placed = tuple(
            jax.device_put(r, NamedSharding(mesh, s))
            for r, s in zip(rows, pl.specs()))
        outs = _gather_jit(pl, placed, specs=pl.specs())
        for k, o in zip(sorted(PARAMS), outs):
            np.testing.assert_array_equal(np.asarray(o),
                                          np.asarray(PARAMS[k]))

    def test_eager_gather_rejects_placed_rows(self):
        from horovod_tpu.common.exceptions import HorovodTpuError

        pl = _placement()
        rows = pl.shard(PARAMS)
        narrowed = tuple(r[:1] for r in rows)
        with pytest.raises(HorovodTpuError, match="in-jit"):
            pl.gather(narrowed)

    def test_quantized_gather_tolerance_and_rank_identity(self):
        """int8 gather wire: every rank decodes the SAME payload, so the
        gathered params are bitwise-identical across ranks and within
        wire tolerance of the exact values."""
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        pl = _placement(gather_wire="int8")
        rows = pl.shard(PARAMS)

        def body(rows):
            t = pl.gather(rows)
            # Stack each rank's gathered copy so the parent can compare
            # all N replicas elementwise.
            return tuple(t[k].ravel()[None] for k in sorted(t))

        sm = shard_map(body, mesh=hvd.global_mesh(), in_specs=(P(),),
                       out_specs=P(hvd.GLOBAL_AXIS), check_vma=False)
        outs = jax.jit(sm)(rows)
        for k, o in zip(sorted(PARAMS), outs):
            per_rank = np.asarray(o)
            assert per_rank.shape[0] == N
            for r in range(1, N):
                np.testing.assert_array_equal(per_rank[r], per_rank[0])
            ref = np.asarray(PARAMS[k]).ravel()
            atol = 0.05 * max(1.0, float(np.abs(ref).max()))
            np.testing.assert_allclose(per_rank[0], ref, atol=atol)


class TestPrefetchOrder:
    def test_reverse_availability_default(self):
        """The partition's first bucket holds the LAST layers (default
        reverse bucket traversal), so the forward consumes back-to-front
        — prefetch_order is the reversed partition order."""
        pl = _placement()
        assert pl.prefetch_order == tuple(
            reversed(range(len(pl.groups))))
        # Default reverse traversal: first group is the largest-index
        # leaves; prefetch starts from the leaf-order front.
        first = pl.groups[pl.prefetch_order[0]]
        assert 0 in first.idxs

    def test_permutation_order_is_permuted_reverse(self):
        """Under bucket_order=<explicit permutation> the prefetch must
        follow the PERMUTED reverse-availability order: the partition
        honors the permutation, and prefetch_order reverses it rather
        than falling back to the leaf order's reverse."""
        from horovod_tpu.parallel.data_parallel import (
            shard_group_partition,
        )

        leaves = list(jax.tree_util.tree_leaves(PARAMS))
        perm = [1, 2, 0]
        base = shard_group_partition(leaves, fusion_threshold_bytes=64,
                                     bucket_order="forward")
        assert len(base) == 3  # every leaf its own group at this cap

        pl = _placement(bucket_order=perm)
        got = [list(g.idxs) for g in pl.groups]
        want = shard_group_partition(leaves, fusion_threshold_bytes=64,
                                     bucket_order=perm)
        assert got == [list(i) for i in want]
        assert pl.prefetch_order == tuple(
            reversed(range(len(pl.groups))))
        # Issue order over GROUP indices realizes the permuted reverse:
        # the last-formed bucket (permutation's tail) gathers first.
        issue = [list(pl.groups[gi].idxs) for gi in pl.prefetch_order]
        assert issue == list(reversed(got))
        # And a roundtrip under the permutation stays bitwise.
        rows = pl.shard(PARAMS)
        back = pl.gather(rows)
        for k, v in PARAMS.items():
            np.testing.assert_array_equal(np.asarray(back[k]),
                                          np.asarray(v))


class TestApplyUpdates:
    def test_compat_and_placed_agree(self):
        from jax import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        pl = _placement()
        rows = pl.shard(PARAMS)
        ups = jax.tree_util.tree_map(lambda x: jnp.ones_like(x) * 0.25,
                                     PARAMS)
        compat = pl.apply_updates(rows, ups)
        back = pl.gather(compat)
        for k, v in PARAMS.items():
            np.testing.assert_array_equal(np.asarray(back[k]),
                                          np.asarray(v) + 0.25)

        mesh = hvd.global_mesh()
        placed = tuple(jax.device_put(r, NamedSharding(mesh, s))
                       for r, s in zip(rows, pl.specs()))
        sm = shard_map(lambda r, u: pl.apply_updates(r, u), mesh=mesh,
                       in_specs=(pl.specs(), P()),
                       out_specs=pl.specs(), check_vma=False)
        placed_out = jax.jit(sm)(placed, ups)
        for a, b in zip(compat, placed_out):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_eager_placed_apply_raises(self):
        from horovod_tpu.common.exceptions import HorovodTpuError

        pl = _placement()
        rows = tuple(r[:1] for r in pl.shard(PARAMS))
        ups = jax.tree_util.tree_map(jnp.zeros_like, PARAMS)
        with pytest.raises(HorovodTpuError, match="in-jit"):
            pl.apply_updates(rows, ups)


class TestGatherMatmul:
    def test_fused_gather_matmul(self):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        w = jnp.arange(32, dtype=jnp.float32).reshape(16, 2) * 0.125
        pl = hvd.zero3_placement({"w": w})
        rows = pl.shard({"w": w})
        x = jnp.ones((3, 2), jnp.float32)

        sm = shard_map(lambda r: pl.gather_matmul(x, r, 0),
                       mesh=hvd.global_mesh(), in_specs=(P(),),
                       out_specs=P(), check_vma=False)
        out = jax.jit(sm)(rows)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w.T),
                                   rtol=1e-6)

    def test_multi_leaf_group_rejected(self):
        pl = _placement(fusion_threshold_bytes=1 << 20)  # one big group
        rows = pl.shard(PARAMS)
        x = jnp.ones((2, 5), jnp.float32)
        with pytest.raises(ValueError, match="single-2D-leaf"):
            pl.gather_matmul(x, rows, 0)

    def test_eager_rejected(self):
        from horovod_tpu.common.exceptions import HorovodTpuError

        w = jnp.ones((16, 2), jnp.float32)
        pl = hvd.zero3_placement({"w": w})
        rows = pl.shard({"w": w})
        with pytest.raises(HorovodTpuError, match="in-jit"):
            pl.gather_matmul(jnp.ones((3, 2), jnp.float32), rows, 0)


class TestBytesAndDrift:
    def test_resident_bytes_ratio(self):
        pl = _placement()
        total = sum(int(np.prod(v.shape)) for v in PARAMS.values()) * 4
        assert pl.full_bytes == total
        # 1/N plus at most one pad row per group.
        assert pl.resident_bytes() <= total // N + 4 * len(pl.groups)
        assert pl.resident_bytes() < pl.full_bytes / 4

    def test_env_default_drift_raises(self, monkeypatch):
        """A placement built on env-default tunables must raise when the
        live fusion threshold moves under it (autotuner proposal)."""
        pl = hvd.zero3_placement(PARAMS)
        rows = pl.shard(PARAMS)
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "64")
        with pytest.raises(ValueError, match="re-init"):
            pl.gather(rows)

    def test_explicit_threshold_immune_to_env(self, monkeypatch):
        pl = _placement()
        rows = pl.shard(PARAMS)
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", str(1 << 20))
        back = pl.gather(rows)
        for k, v in PARAMS.items():
            np.testing.assert_array_equal(np.asarray(back[k]),
                                          np.asarray(v))

    def test_row_shape_drift_raises(self):
        pl = _placement()
        rows = pl.shard(PARAMS)
        wrong = (rows[0][:, :-1],) + tuple(rows[1:])
        with pytest.raises(ValueError, match="re-init"):
            pl.gather(wrong)

    def test_group_count_drift_raises(self):
        pl = _placement()
        rows = pl.shard(PARAMS)
        with pytest.raises(ValueError, match="re-init"):
            pl.gather(rows[:-1])

    def test_param_resident_gauge_set(self):
        from horovod_tpu.metrics import catalog as met

        pl = _placement()
        rows = pl.shard(PARAMS)
        met.param_resident_bytes.set(0)
        _gather_jit(pl, rows)
        assert met.param_resident_bytes._solo().get() == \
            pl.resident_bytes()


class TestValidation:
    def test_cooperative_wire_needs_flat_axis(self):
        with pytest.raises(ValueError, match="ONE named axis"):
            hvd.zero3_placement(PARAMS, axis_name=("dcn", "hvd"),
                                gather_wire="int8")

    def test_global_process_set_required(self):
        ps = hvd.add_process_set([0, 2])
        try:
            with pytest.raises(ValueError, match="global process"):
                hvd.zero3_placement(PARAMS, process_set=ps)
        finally:
            hvd.remove_process_set(ps)

    def test_tree_mismatch_raises(self):
        pl = _placement()
        with pytest.raises(ValueError, match="tree"):
            pl.shard({"other": jnp.zeros((3,), jnp.float32)})

    def test_env_gather_wire(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_ZERO_GATHER_WIRE", "int8")
        pl = _placement()
        assert pl.gather_wire == "int8"
