"""Input-prefetcher tests (utils/prefetch.py): device placement, stream
order, look-ahead, exception propagation, producer-thread lifecycle.

Reference analog: the Spark async data loaders
(spark/data_loaders/pytorch_data_loaders.py) and the synthetic
benchmark's pre-staged device batches.
"""

import numpy as np
import pytest

import jax

import horovod_tpu as hvd
from horovod_tpu.utils.prefetch import (
    BackgroundPrefetcher,
    prefetch_to_device,
)


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


def _host_batches(n, batch=8):
    for i in range(n):
        yield {"x": np.full((batch, 4), i, np.float32),
               "y": np.arange(batch, dtype=np.int32)}


class TestPrefetchToDevice:
    def test_stream_order_and_values(self):
        out = list(prefetch_to_device(_host_batches(5), size=2))
        assert len(out) == 5
        for i, b in enumerate(out):
            np.testing.assert_array_equal(
                np.asarray(b["x"]), np.full((8, 4), i, np.float32))

    def test_batches_are_sharded_on_mesh(self):
        (b,) = list(prefetch_to_device(_host_batches(1), size=2))
        x = b["x"]
        assert isinstance(x, jax.Array)
        # dim 0 split over the 8-rank axis: each shard holds 1 row.
        assert len(x.addressable_shards) == hvd.size()
        assert x.addressable_shards[0].data.shape == (1, 4)

    def test_feeds_data_parallel_step(self):
        step = hvd.data_parallel(
            lambda b: hvd.allreduce(b["x"].sum()))
        for b in prefetch_to_device(_host_batches(3), size=2):
            out = step(b)
        assert np.isfinite(float(out))

    def test_size_one_and_short_stream(self):
        assert len(list(prefetch_to_device(_host_batches(1), size=4))) == 1
        assert list(prefetch_to_device(iter([]), size=2)) == []

    def test_bad_size(self):
        with pytest.raises(ValueError):
            list(prefetch_to_device(_host_batches(1), size=0))

    def test_custom_sharding_replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from horovod_tpu.common import basics

        s = NamedSharding(basics.global_mesh(), P())
        (b,) = list(prefetch_to_device(_host_batches(1), sharding=s))
        assert b["x"].sharding.is_fully_replicated

    def test_source_exception_propagates(self):
        def bad():
            yield {"x": np.zeros((8, 4), np.float32)}
            raise RuntimeError("decode failed")

        it = prefetch_to_device(bad(), size=1)
        next(it)
        with pytest.raises(RuntimeError, match="decode failed"):
            next(it)


class TestBackgroundPrefetcher:
    def test_stream_order(self):
        with BackgroundPrefetcher(_host_batches(6), size=2) as it:
            vals = [float(np.asarray(b["x"])[0, 0]) for b in it]
        assert vals == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]

    def test_exception_reraises_in_order(self):
        def bad():
            yield {"x": np.ones((8, 4), np.float32)}
            raise ValueError("boom")

        p = BackgroundPrefetcher(bad(), size=2)
        it = iter(p)
        next(it)
        with pytest.raises(ValueError, match="boom"):
            next(it)

    def test_close_unblocks_producer(self):
        p = BackgroundPrefetcher(_host_batches(100), size=1)
        it = iter(p)
        next(it)
        p.close()  # must not hang on the full queue

    def test_second_iteration_returns_immediately(self):
        p = BackgroundPrefetcher(_host_batches(2), size=2)
        assert len(list(iter(p))) == 2
        assert list(iter(p)) == []  # must not hang on a spent sentinel
