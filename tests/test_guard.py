"""Training-health guardian (horovod_tpu/guard/, docs/GUARD.md): fused
non-finite sentinel, coordinated skip-step with dynamic loss scaling,
cross-replica digest divergence detection, and the rollback ladder.

Fast tests run on the 8-virtual-rank mesh (conftest.py); the real
np=2 cross-process drill lives in TestGuardCrossProcess at the bottom
(tests/data/guard_main.py).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import faults
from horovod_tpu.guard import (
    DynamicLossScale,
    GuardState,
    TrainingGuard,
    bucket_flags_local,
    check_replica_divergence,
    crossrank_or,
    local_nonfinite,
    param_digests,
    select_on_flag,
    sliced_nonfinite,
)
from horovod_tpu.parallel.data_parallel import allreduce_gradients

N = 8  # virtual ranks (conftest XLA_FLAGS)
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# DynamicLossScale / GuardState schedule
# ---------------------------------------------------------------------------

def test_loss_scale_backoff_and_growth():
    s = DynamicLossScale(init_scale=1024.0, growth_interval=2)
    gs = s.init(3)
    assert float(gs.loss_scale) == 1024.0
    assert gs.bucket_flags.shape == (3,)

    gs = s.update(gs, jnp.array([0.0, 1.0, 0.0]))  # overflow
    assert float(gs.loss_scale) == 512.0
    assert int(gs.nonfinite_steps) == 1
    assert int(gs.good_steps) == 0

    gs = s.update(gs, jnp.zeros(3))                # clean
    assert float(gs.loss_scale) == 512.0
    assert int(gs.nonfinite_steps) == 0
    assert int(gs.good_steps) == 1

    gs = s.update(gs, jnp.zeros(3))                # 2nd clean -> grow
    assert float(gs.loss_scale) == 1024.0
    assert int(gs.good_steps) == 0


def test_consecutive_nonfinite_counter():
    s = DynamicLossScale(init_scale=4.0, growth_interval=100)
    gs = s.init(1)
    for k in range(3):
        gs = s.update(gs, jnp.ones(1))
        assert int(gs.nonfinite_steps) == k + 1
    gs = s.update(gs, jnp.zeros(1))
    assert int(gs.nonfinite_steps) == 0  # CONSECUTIVE, not cumulative


def test_static_scale_never_moves():
    s = DynamicLossScale(init_scale=1.0, dynamic=False)
    gs = s.init(1)
    gs = s.update(gs, jnp.ones(1))
    assert float(gs.loss_scale) == 1.0
    assert int(gs.nonfinite_steps) == 1  # skip-step ladder still counts


def test_from_env(monkeypatch):
    monkeypatch.delenv("HOROVOD_GUARD_LOSS_SCALE", raising=False)
    s = DynamicLossScale.from_env()
    assert s.init_scale == 1.0 and not s.dynamic
    monkeypatch.setenv("HOROVOD_GUARD_LOSS_SCALE", "2048")
    s = DynamicLossScale.from_env()
    assert s.init_scale == 2048.0 and s.dynamic


def test_pending_flag_bridges_passes():
    """An early-reduction pass flag must gate the NEXT update even when
    the sync pass itself reduces clean."""
    s = DynamicLossScale(init_scale=64.0, growth_interval=100)
    gs = s.accumulate(s.init(1), jnp.ones(1))
    assert float(gs.pending_flag) == 1.0
    gs = s.update(gs, jnp.zeros(1))
    assert float(gs.loss_scale) == 32.0       # pending counted as bad
    assert float(gs.pending_flag) == 0.0      # consumed


def test_select_on_flag():
    clean = {"a": jnp.ones(2)}
    old = {"a": jnp.zeros(2)}
    out = select_on_flag(jnp.asarray(1.0), clean, old)
    assert (np.asarray(out["a"]) == 0).all()
    out = select_on_flag(jnp.asarray(0.0), clean, old)
    assert (np.asarray(out["a"]) == 1).all()


# ---------------------------------------------------------------------------
# Sentinel primitives
# ---------------------------------------------------------------------------

def test_local_nonfinite_scalar():
    assert float(local_nonfinite([jnp.ones(3)])) == 0.0
    assert float(local_nonfinite([jnp.array([1.0, jnp.nan])])) == 1.0
    assert float(local_nonfinite([jnp.array([jnp.inf])])) == 1.0
    # Integer leaves carry no non-finite values and must not upcast.
    assert float(local_nonfinite([jnp.arange(3)])) == 0.0
    assert float(local_nonfinite([])) == 0.0


def test_bucket_flags_local_attribution():
    leaves = [jnp.ones(4), jnp.array([jnp.nan, 1.0]), jnp.ones(2)]
    flags = bucket_flags_local(leaves, [[0, 2], [1]])
    assert np.asarray(flags).tolist() == [0.0, 1.0]


def test_sentinel_flags_cross_rank_or(mesh):
    """A NaN on ONE rank's gradient shard must flag ALL ranks (bitwise
    0/1 Max-OR inside the compiled reduction)."""
    data = np.ones((N, 4), np.float32)
    data[3, 0] = np.nan  # rank 3 only

    def body(x):
        out, flags = allreduce_gradients({"g": x[0]}, sentinel=True)
        return out["g"], flags

    sm = jax.shard_map(
        body, mesh=mesh, in_specs=(P(hvd.GLOBAL_AXIS),),
        out_specs=(P(), P()), check_vma=False)
    _, flags = jax.jit(sm)(jnp.asarray(data))
    assert np.asarray(flags).tolist() == [1.0]

    _, flags = jax.jit(sm)(jnp.ones((N, 4), jnp.float32))
    assert np.asarray(flags).tolist() == [0.0]


def test_sliced_nonfinite_full_coverage(mesh):
    """The sliced scan (each participant checks its 1/N interleave of a
    replicated buffer) + cross-rank OR must still catch a non-finite at
    EVERY position, including the non-divisible tail."""
    def body(x):
        f = sliced_nonfinite([x], hvd.GLOBAL_AXIS)
        return crossrank_or(jnp.stack([f]), axis_name=hvd.GLOBAL_AXIS)

    sm = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(),), out_specs=P(),
        check_vma=False))
    clean = jnp.arange(33.0)  # 33 % 8 != 0: exercises the tail
    assert np.asarray(sm(clean)).tolist() == [0.0]
    for i in range(33):
        assert np.asarray(sm(clean.at[i].set(jnp.nan))).tolist() == [1.0], i
    # Eager fallback (no axis in scope) degrades to the full local scan.
    assert float(sliced_nonfinite([jnp.array([1.0, jnp.inf])])) == 1.0
    assert float(sliced_nonfinite([jnp.arange(3)])) == 0.0


# ---------------------------------------------------------------------------
# Guarded optimizer: coordinated skip-step inside the compiled step
# ---------------------------------------------------------------------------

def _compiled_step(opt, mesh, scale_loss=True):
    def loss_fn(w, x, y, scale):
        return jnp.mean((x @ w - y) ** 2) * scale

    def step(w, opt_state, x, y):
        scale = (opt_state.guard.loss_scale if scale_loss
                 else jnp.float32(1.0))
        grads = jax.grad(loss_fn)(w, x, y, scale)
        updates, opt_state = opt.update(grads, opt_state, w)
        return optax.apply_updates(w, updates), opt_state

    sm = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(hvd.GLOBAL_AXIS), P(hvd.GLOBAL_AXIS)),
        out_specs=(P(), P()), check_vma=False)
    return jax.jit(sm)


def _data(poison_rank=None):
    rng = np.random.RandomState(0)
    xs = rng.uniform(size=(N * 2, 4)).astype(np.float32)
    ys = rng.uniform(size=(N * 2,)).astype(np.float32)
    if poison_rank is not None:
        xs = xs.copy()
        xs[poison_rank * 2, 0] = np.nan
    return jnp.asarray(xs), jnp.asarray(ys)


@pytest.mark.parametrize("extra", [
    {},
    {"fused_apply": True},
    {"shard_optimizer_states": True},
], ids=["plain", "fused", "sharded"])
def test_skip_step_and_decay(mesh, extra):
    scaler = DynamicLossScale(init_scale=1024.0, growth_interval=100)
    opt = hvd.DistributedOptimizer(optax.sgd(0.1), guard=scaler, **extra)
    compiled = _compiled_step(opt, mesh)
    w = jnp.zeros((4,), jnp.float32)
    state = opt.init(w)
    xs, ys = _data()

    w1, state = compiled(w, state, xs, ys)          # clean
    w1_host = np.asarray(w1)
    assert float(state.guard.loss_scale) == 1024.0
    assert (w1_host != 0).any()

    bad_xs, _ = _data(poison_rank=5)
    w2, state = compiled(w1, state, bad_xs, ys)     # flagged
    assert (np.asarray(w2) == w1_host).all()        # apply skipped
    assert float(state.guard.loss_scale) == 512.0
    assert int(state.guard.nonfinite_steps) == 1
    assert float(np.asarray(state.guard.bucket_flags).max()) == 1.0

    w3, state = compiled(w2, state, xs, ys)         # recovered
    assert np.isfinite(np.asarray(w3)).all()
    assert (np.asarray(w3) != w1_host).any()
    assert int(state.guard.nonfinite_steps) == 0


def test_skipped_step_preserves_inner_state(mesh):
    """Adam moments must not absorb the poisoned gradients."""
    scaler = DynamicLossScale(init_scale=256.0, growth_interval=100)
    opt = hvd.DistributedOptimizer(optax.adam(1e-2), guard=scaler)
    compiled = _compiled_step(opt, mesh)
    w = jnp.zeros((4,), jnp.float32)
    state = opt.init(w)
    xs, ys = _data()
    w, state = compiled(w, state, xs, ys)
    inner_before = jax.tree_util.tree_map(np.asarray, state.inner)

    bad_xs, _ = _data(poison_rank=0)
    w, state = compiled(w, state, bad_xs, ys)
    inner_after = jax.tree_util.tree_map(np.asarray, state.inner)
    for a, b in zip(jax.tree_util.tree_leaves(inner_before),
                    jax.tree_util.tree_leaves(inner_after)):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_guard_static_scale_bitwise_equals_unguarded(mesh):
    """Acceptance: with the static 1.0 schedule (skip-step only) and no
    faults, the trajectory must be BITWISE identical to the unguarded
    pipeline — the sentinel/gate must not perturb a single bit.
    Dyadic hyperparameters + integral gradients (the TestShardedOptimizer
    idiom) keep every intermediate exactly representable, so XLA's
    freedom to contract mul+add to FMA differently in the two program
    shapes cannot cost a ulp."""
    rng = np.random.RandomState(0)
    grads = jnp.asarray(np.round(rng.randn(N, 16) * 4), jnp.float32)

    def run(guard):
        opt = hvd.DistributedOptimizer(optax.sgd(0.25, momentum=0.5),
                                       guard=guard)

        def body(g):
            w = jnp.zeros((16,), jnp.float32)
            state = opt.init(w)
            for _ in range(4):
                u, state = opt.update(g[0], state, w)
                w = w + u
            return w, jnp.stack(jax.tree_util.tree_leaves(state.inner))

        sm = jax.shard_map(body, mesh=mesh,
                           in_specs=(P(hvd.GLOBAL_AXIS),),
                           out_specs=(P(), P()), check_vma=False)
        w, inner = jax.jit(sm)(grads)
        return np.asarray(w), np.asarray(inner)

    w_off, inner_off = run(False)
    w_on, inner_on = run(DynamicLossScale(init_scale=1.0, dynamic=False))
    assert w_off.tobytes() == w_on.tobytes()
    assert inner_off.tobytes() == inner_on.tobytes()


def test_early_reduction_pending_flag_skips_megastep(mesh):
    """A NaN in accumulation pass 1 of 2 must skip the whole fused
    apply on the sync pass (pending_flag bridge)."""
    scaler = DynamicLossScale(init_scale=128.0, growth_interval=100)
    opt = hvd.DistributedOptimizer(
        optax.sgd(0.1), guard=scaler, backward_passes_per_step=2,
        early_reduction=True)
    compiled = _compiled_step(opt, mesh)
    w = jnp.zeros((4,), jnp.float32)
    state = opt.init(w)
    xs, ys = _data()
    bad_xs, _ = _data(poison_rank=2)

    w, state = compiled(w, state, bad_xs, ys)   # pass 1 (poisoned)
    w, state = compiled(w, state, xs, ys)       # pass 2 -> sync apply
    assert (np.asarray(w) == 0).all()           # megastep skipped
    assert float(state.guard.loss_scale) == 64.0

    w, state = compiled(w, state, xs, ys)       # clean megastep
    w, state = compiled(w, state, xs, ys)
    assert (np.asarray(w) != 0).any()
    assert float(state.guard.loss_scale) == 64.0


def test_early_reduction_body_sentinel_flags():
    """megastep.early_reduction_body(sentinel=True) returns the
    per-pass OR of bucket flags alongside the accumulated total."""
    from horovod_tpu.utils.megastep import early_reduction_body

    def grad_fn(params, batch):
        return {"w": params["w"] * batch}

    params = {"w": jnp.ones((3,), jnp.float32)}
    batches = jnp.stack([jnp.float32(1.0), jnp.float32(jnp.nan)])
    total, flags = early_reduction_body(grad_fn, 2, sentinel=True)(
        params, batches)
    assert float(np.asarray(flags).max()) == 1.0
    clean = jnp.stack([jnp.float32(1.0), jnp.float32(2.0)])
    total, flags = early_reduction_body(grad_fn, 2, sentinel=True)(
        params, clean)
    assert float(np.asarray(flags).max()) == 0.0
    np.testing.assert_allclose(np.asarray(total["w"]), 1.5)  # averaged


# ---------------------------------------------------------------------------
# Digests
# ---------------------------------------------------------------------------

def test_param_digests_bit_sensitive():
    params = {"a": np.ones((4,), np.float32),
              "b": np.arange(6, dtype=np.float32)}
    d1 = param_digests(params)
    d2 = param_digests(params)
    assert d1.shape[1] == 2 and (d1 == d2).all()

    flipped = {"a": params["a"].copy(), "b": params["b"]}
    bits = flipped["a"].view(np.uint32)
    bits[0] ^= np.uint32(1 << 20)
    d3 = param_digests(flipped)
    assert (d1 != d3).any()


def test_digest_check_single_process_is_noop():
    d = param_digests({"w": np.ones(3, np.float32)})
    assert check_replica_divergence(d) is None


# ---------------------------------------------------------------------------
# TrainingGuard: host-side ladder
# ---------------------------------------------------------------------------

def _gs(scale=512.0, nonfinite=0, flags=(0.0,)):
    return GuardState(
        loss_scale=jnp.asarray(scale, jnp.float32),
        good_steps=jnp.zeros((), jnp.int32),
        nonfinite_steps=jnp.asarray(nonfinite, jnp.int32),
        bucket_flags=jnp.asarray(flags, jnp.float32),
        pending_flag=jnp.zeros((), jnp.float32))


def test_observe_reads_verdict_and_escalates():
    tg = TrainingGuard(scaler=DynamicLossScale(), digest_interval=0,
                       max_nonfinite=2)
    v = tg.observe(_gs(), {"w": np.ones(3)}, step=1)
    assert not v.flagged and not v.rollback and v.loss_scale == 512.0

    v = tg.observe(_gs(nonfinite=1, flags=(1.0,)), {"w": np.ones(3)}, 2)
    assert v.flagged and v.nonfinite_steps == 1 and not v.rollback

    v = tg.observe(_gs(nonfinite=2, flags=(1.0,)), {"w": np.ones(3)}, 3)
    assert v.rollback  # K consecutive -> escalate


def test_maybe_inject_translates_faults():
    tg = TrainingGuard(scaler=DynamicLossScale(), digest_interval=0)
    batch = {"x": jnp.ones((2, 2), jnp.float32)}
    params = {"w": jnp.ones((3,), jnp.float32)}
    try:
        faults.install("guard.nan_grad@1:err")
        b2, p2 = tg.maybe_inject(batch, params)
        assert np.isnan(np.asarray(b2["x"])[0, 0])
        assert (np.asarray(p2["w"]) == 1).all()

        faults.install("guard.param_bitflip@1:err")
        b3, p3 = tg.maybe_inject(batch, params)
        assert (np.asarray(b3["x"]) == 1).all()
        old = np.asarray(params["w"]).view(np.uint32)
        new = np.asarray(p3["w"]).view(np.uint32)
        assert np.isfinite(np.asarray(p3["w"])).all()
        assert (old != new).sum() == 1  # exactly one word differs
        assert bin(int(old[0] ^ new[0])).count("1") == 1  # by one bit
    finally:
        faults.clear()
    # Disarmed: zero-overhead no-op.
    b4, p4 = tg.maybe_inject(batch, params)
    assert b4 is batch and p4 is params


def test_rollback_restores_resets_and_bumps_generation(tmp_path):
    from horovod_tpu.ops import wire

    tg = TrainingGuard(scaler=DynamicLossScale(),
                       checkpoint_dir=str(tmp_path), digest_interval=0)
    state = {"w": np.arange(4, dtype=np.float32)}
    assert tg.checkpoint(3, state)
    assert tg.last_verified_step == 3

    calls = []
    hook = lambda: calls.append(1)  # noqa: E731
    wire.register_error_feedback_reset(hook)
    try:
        gen0 = wire.error_feedback_generation()
        restored = tg.rollback(template=state)
    finally:
        wire.unregister_error_feedback_reset(hook)
    assert (np.asarray(restored["w"]) == state["w"]).all()
    assert tg.generation == 1
    assert calls == [1]  # EF residuals invalidated
    assert wire.error_feedback_generation() == gen0 + 1


def test_reset_guard_state_reseeds():
    scaler = DynamicLossScale(init_scale=1024.0, growth_interval=100)
    opt = hvd.DistributedOptimizer(optax.sgd(0.1), guard=scaler)
    state = opt.init(jnp.zeros((4,), jnp.float32))
    dirty = state._replace(guard=_gs(scale=2.0, nonfinite=7))
    fresh = TrainingGuard.reset_guard_state(dirty, scaler)
    assert float(fresh.guard.loss_scale) == 1024.0
    assert int(fresh.guard.nonfinite_steps) == 0
    assert fresh.guard.bucket_flags.shape == \
        dirty.guard.bucket_flags.shape


# ---------------------------------------------------------------------------
# Satellites: quarantine cap, consistency timeout, wire reset hooks
# ---------------------------------------------------------------------------

def test_quarantine_pruned_to_newest_keep(tmp_path, monkeypatch):
    from horovod_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path))
    for s in range(1, 6):
        (tmp_path / f"step_{s}.corrupt").mkdir()
    monkeypatch.setenv("HOROVOD_CKPT_QUARANTINE_KEEP", "2")
    mgr._prune_quarantine()
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == ["step_4.corrupt", "step_5.corrupt"]

    # keep=0 empties the quarantine entirely.
    monkeypatch.setenv("HOROVOD_CKPT_QUARANTINE_KEEP", "0")
    mgr._prune_quarantine()
    assert list(tmp_path.iterdir()) == []


def test_quarantine_moves_then_prunes(tmp_path, monkeypatch):
    from horovod_tpu.utils.checkpoint import CheckpointManager

    monkeypatch.setenv("HOROVOD_CKPT_QUARANTINE_KEEP", "1")
    mgr = CheckpointManager(str(tmp_path))
    for s in (1, 2):
        (tmp_path / f"step_{s}").mkdir()
        mgr._quarantine(s)
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == ["step_2.corrupt"]


def test_consistency_timeout_from_env(monkeypatch):
    from horovod_tpu.utils import consistency

    monkeypatch.delenv("HOROVOD_CONSISTENCY_TIMEOUT", raising=False)
    assert consistency._timeout_s() == 30.0
    monkeypatch.setenv("HOROVOD_CONSISTENCY_TIMEOUT", "2.5")
    assert consistency._timeout_s() == 2.5  # read per check, live


def test_wire_reset_hooks_register_unregister():
    from horovod_tpu.ops import wire

    calls = []
    hook = lambda: calls.append(1)  # noqa: E731
    wire.register_error_feedback_reset(hook)
    g0 = wire.error_feedback_generation()
    assert wire.reset_error_feedback() == g0 + 1
    assert calls == [1]
    wire.unregister_error_feedback_reset(hook)
    wire.reset_error_feedback()
    assert calls == [1]  # unregistered hooks stay silent


# ---------------------------------------------------------------------------
# REAL np=2 cross-process drill
# ---------------------------------------------------------------------------

GUARD_WORKER = os.path.join(REPO_ROOT, "tests", "data", "guard_main.py")


@pytest.mark.integration
class TestGuardCrossProcess:
    """End-to-end ladder under real gloo collectives: rank-1-only NaN
    injection -> both ranks skip the SAME step and decay the SAME loss
    scale; rank-1-only bit-flip -> digest mismatch -> both ranks roll
    back to the digest-verified checkpoint; bitwise-identical finish."""

    def test_nan_skip_and_bitflip_rollback(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + \
            env.get("PYTHONPATH", "")
        env["HVD_TEST_OUT"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "python", GUARD_WORKER],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO_ROOT)
        assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
        res = {}
        for rank in (0, 1):
            path = tmp_path / f"rank{rank}.json"
            assert path.exists(), \
                f"rank {rank} wrote no result:\n{r.stdout}\n{r.stderr}"
            res[rank] = json.loads(path.read_text())

        # Lockstep: the whole per-step trace is identical across ranks.
        assert res[0]["trace"] == res[1]["trace"]
        by_step = {t["step"]: t for t in res[0]["trace"]}
        # Phase A: only step 3 (rank 1's NaN injection) flags; both
        # ranks decay 1024 -> 512 together.
        assert [t["step"] for t in res[0]["trace"] if t["flagged"]] == [3]
        assert by_step[2]["scale"] == 1024.0
        assert by_step[3]["scale"] == 512.0
        assert by_step[3]["nonfinite"] == 1
        assert by_step[4]["scale"] == 512.0
        assert by_step[4]["nonfinite"] == 0
        # Phase B: the step-8 digest check catches rank 1's bit-flip,
        # attributes it, and both ranks roll back to step 4's snapshot.
        for rank in (0, 1):
            assert res[rank]["rollback_at"] == 8, res[rank]
            assert res[rank]["mismatch_bucket"] == 0
            assert res[rank]["generation"] == 1
            assert res[rank]["last_verified_step"] == 4
            assert res[rank]["final_digest_clean"], res[rank]
            assert np.isfinite(res[rank]["final_w"]).all()
        # Bitwise-identical final parameters across ranks.
        assert res[0]["final_w"] == res[1]["final_w"]
