"""Live resharding unit tests (parallel/reshard.py, docs/RESHARD.md):
plan geometry, the peak-bounded LocalTransport exchange, integrity
failures (corrupt chunk, dead peer), the EF fold rule, scenario (c)
local restack, and the scenario (b) decode handoff."""

import numpy as np
import pytest

import horovod_tpu.faults as faults
from horovod_tpu.common.exceptions import HorovodTpuError, ReshardError
from horovod_tpu.parallel import reshard as rs
from horovod_tpu.parallel.optimizer import (
    DistributedOptState, _ShardSlot, _WireEF, _ZeroAccum,
)


def _ranges(elems, n):
    return [rs._owned_range(elems, n, r) for r in range(n)]


# ---------------------------------------------------------------------------
# plan geometry


@pytest.mark.parametrize("elems,n_old,n_new", [
    (10, 2, 3), (10, 3, 2), (10, 4, 4), (5, 8, 2), (5, 2, 8),
    (1, 2, 3), (64, 1, 4), (64, 4, 1), (7, 3, 5),
])
def test_plan_fetch_covers_new_range_exactly(elems, n_old, n_new):
    spec = rs.StreamSpec("p0", elems, "float32", "shard")
    plan = rs.ReshardPlan([spec], n_old, n_new, chunk_bytes=12,
                          peak_bytes=1 << 20)
    published = {
        (iv.src, iv.start, iv.stop)
        for r in range(n_old)
        for iv in plan.publish_intervals(spec, r)}
    # published payloads tile each old rank's range exactly
    for r in range(n_old):
        lo, hi = rs._owned_range(elems, n_old, r)
        ivs = sorted(i for i in published if i[0] == r)
        assert sum(b - a for _, a, b in ivs) == hi - lo
    for r in range(n_new):
        lo, hi = rs._owned_range(elems, n_new, r)
        got = plan.fetch_intervals(spec, r)
        # disjoint, sorted coverage of [lo, hi)
        covered = sorted((iv.start, iv.stop) for iv in got)
        assert sum(b - a for a, b in covered) == hi - lo
        if covered:
            assert covered[0][0] == lo and covered[-1][1] == hi
        # every fetch interval maps onto one published payload
        for iv in got:
            pub = rs._fix_grid_cut_overlap(plan, spec, iv)
            assert (pub.src, pub.start, pub.stop) in published


def test_perrank_fetch_sources_partition_old_ranks():
    spec = rs.StreamSpec("e0", 9, "float32", "perrank")
    plan = rs.ReshardPlan([spec], 5, 2, chunk_bytes=64)
    srcs = [sorted({iv.src for iv in plan.fetch_intervals(spec, r)})
            for r in range(2)]
    assert srcs == [[0, 2, 4], [1, 3]]   # r ≡ j (mod n_new), ascending


# ---------------------------------------------------------------------------
# end-to-end over LocalTransport


def _fetch_all(specs, n_old, n_new, t, **kw):
    """Run every new rank's fetch concurrently (the verdict barrier
    needs every rank's recv_ok, so sequential fetches would deadlock —
    exactly as they would in production)."""
    import threading
    outs = [None] * n_new
    reports = [None] * n_new
    errs = []

    def _one(r):
        try:
            outs[r], reports[r] = rs.reshard_streams(
                specs, None, n_old, n_new, None, r, t, **kw)
        except Exception as e:  # noqa: BLE001 — re-raised below
            errs.append(e)

    threads = [threading.Thread(target=_one, args=(r,))
               for r in range(n_new)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    if errs:
        raise errs[0]
    return outs, reports


def _move(specs, per_old_data, n_old, n_new, **kw):
    """Single-process reshard: publish every old rank sequentially,
    then fetch every new rank concurrently."""
    t = rs.LocalTransport()
    reports = []
    for r in range(n_old):
        _, rep = rs.reshard_streams(specs, per_old_data[r], n_old,
                                    n_new, r, None, t, **kw)
        reports.append(rep)
    outs, freps = _fetch_all(specs, n_old, n_new, t, **kw)
    return outs, reports + freps


def _shard_data(spec, buf, n_old):
    out = []
    for r in range(n_old):
        lo, hi = rs._owned_range(spec.elems, n_old, r)
        out.append({spec.name: buf[lo:hi]})
    return out


@pytest.mark.parametrize("n_old,n_new", [(2, 1), (1, 2), (3, 2)])
def test_transport_roundtrip_bitwise(n_old, n_new):
    rng = np.random.RandomState(7)
    buf = rng.uniform(-1, 1, size=(37,)).astype(np.float32)
    spec = rs.StreamSpec("p0", buf.size, "float32", "shard")
    outs, _ = _move([spec], _shard_data(spec, buf, n_old), n_old,
                    n_new, chunk_bytes=16, peak_bytes=1 << 16)
    got = np.concatenate([outs[r][spec.name] for r in range(n_new)])
    assert got.tobytes() == buf.tobytes()


def test_peak_is_measured_and_bounded():
    buf = np.arange(4096, dtype=np.float32)
    spec = rs.StreamSpec("p0", buf.size, "float32", "shard")
    peak = 4096                                 # forces 1 KiB chunks
    outs, reports = _move([spec], _shard_data(spec, buf, 2), 2, 1,
                          chunk_bytes=None, peak_bytes=peak)
    assert outs[0][spec.name].tobytes() == buf.tobytes()
    assert all(r.chunks > 1 for r in reports)
    assert all(0 < r.peak_bytes <= peak for r in reports)


def test_peak_overrun_raises():
    plan = rs.ReshardPlan(
        [rs.StreamSpec("p0", 8, "float32", "shard")], 1, 1)
    tr = rs._PeakTracker()
    tr.add(plan.peak_bytes + 1)
    assert tr.peak > plan.peak_bytes   # executor turns this into
    #                                    ReshardError (exercised below
    #                                    via the ceiling test)


def test_chunk_corrupt_detected():
    buf = np.arange(64, dtype=np.float32)
    spec = rs.StreamSpec("p0", buf.size, "float32", "shard")
    faults.install("reshard.chunk_corrupt:err")
    try:
        with pytest.raises(ReshardError, match="sha256|corrupt"):
            _move([spec], _shard_data(spec, buf, 2), 2, 1,
                  chunk_bytes=64, timeout=2.0)
        assert faults.points_hit("reshard.chunk_corrupt") > 0
    finally:
        faults.clear()


def test_peer_die_leaves_fetchers_timing_out():
    buf = np.arange(64, dtype=np.float32)
    spec = rs.StreamSpec("p0", buf.size, "float32", "shard")
    t = rs.LocalTransport()
    rs.reshard_streams([spec], {spec.name: buf[:32]}, 2, 1, 0, None, t,
                       chunk_bytes=64)
    faults.install("reshard.peer_die:err")
    try:
        with pytest.raises(faults.FaultInjected):
            rs.reshard_streams([spec], {spec.name: buf[32:]}, 2, 1, 1,
                               None, t, chunk_bytes=64)
    finally:
        faults.clear()
    # rank 1 died mid-publish: the fetcher must NOT assemble state —
    # it fails deterministically (fail marker or timeout).
    with pytest.raises(ReshardError):
        rs.reshard_streams([spec], None, 2, 1, None, 0, t,
                           chunk_bytes=64, timeout=1.0)


def test_digest_mismatch_detected():
    buf = np.arange(16, dtype=np.float32)
    spec = rs.StreamSpec("p0", buf.size, "float32", "shard")
    t = rs.LocalTransport()
    rs.reshard_streams([spec], {spec.name: buf[:8]}, 2, 1, 0, None, t,
                       chunk_bytes=64)
    rs.reshard_streams([spec], {spec.name: buf[8:]}, 2, 1, 1, None, t,
                       chunk_bytes=64)
    # Flip one payload for a chunk whose sha still verifies: re-encode
    # different data under the same key (simulates a publisher bug /
    # torn write the per-chunk sha cannot see).
    key = [k for k in t.keys("g/p0/") if "digest" not in k][0]
    evil = buf[:8].copy()
    evil[0] += 1
    t.put(key, rs._encode_payload(evil, None, rs._PeakTracker()))
    with pytest.raises(ReshardError, match="digest"):
        rs.reshard_streams([spec], None, 2, 1, None, 0, t,
                           chunk_bytes=64, timeout=2.0)


# ---------------------------------------------------------------------------
# digests


def test_bitsum_digest_order_free_and_exact():
    rng = np.random.RandomState(3)
    a = rng.uniform(size=(1001,)).astype(np.float32)
    whole = rs.bitsum_digest(a)
    parts = [rs.bitsum_digest(a[:301]), rs.bitsum_digest(a[301:800]),
             rs.bitsum_digest(a[800:])]
    assert rs._combine_digests(parts) == whole
    assert rs._combine_digests(list(reversed(parts))) == whole
    b = a.copy()
    b[500] = np.nextafter(b[500], 2.0, dtype=np.float32)
    assert rs.bitsum_digest(b) != whole


# ---------------------------------------------------------------------------
# EF fold rule


def test_ef_fold_conserves_residual_on_shrink():
    rows = np.arange(4 * 12, dtype=np.float32).reshape(4, 12)
    folded = rs.reshard_ef_rows(rows, elems=10, n_new=2)
    assert folded.shape == (2, 10)
    assert folded.dtype == np.float32
    np.testing.assert_array_equal(folded[0], rows[0, :10] + rows[2, :10])
    np.testing.assert_array_equal(folded[1], rows[1, :10] + rows[3, :10])
    # total residual conserved (integer-valued → exact)
    assert folded.sum() == rows[:, :10].sum()


def test_ef_fold_zeroes_joiners_on_grow():
    rows = np.arange(2 * 10, dtype=np.float32).reshape(2, 10)
    grown = rs.reshard_ef_rows(rows, elems=10, n_new=4)
    np.testing.assert_array_equal(grown[0, :10], rows[0])
    np.testing.assert_array_equal(grown[1, :10], rows[1])
    assert not grown[2:].any()


def test_replicated_divergence_raises():
    rows = np.array([3, 3, 4], dtype=np.int32)
    with pytest.raises(ReshardError, match="replicated"):
        rs.reshard_replicated_rows(rows, 2)
    np.testing.assert_array_equal(
        rs.reshard_replicated_rows(np.array([5, 5]), 3),
        np.array([5, 5, 5]))


# ---------------------------------------------------------------------------
# scenario (c): local restack of a full compat optimizer state


def _synthetic_state(n, group_elems=(10, 7), ef_gen=0):
    """Hand-built compat DistributedOptState: adam-ish per-element
    leaves + one replicated scalar per group, masters on group 0, EF on
    group 0 only.  Integer-valued floats keep every fold exact."""
    rng = np.random.RandomState(42 + n)

    def _rows(lo, hi, elems, s):
        # real init pads the flat buffer with zeros — mirror that, or
        # a restack round trip would "lose" the garbage padding
        a = rng.randint(lo, hi, size=(n * s,)).astype(np.float32)
        a[elems:] = 0
        return a.reshape(n, s)

    slots, accum, ef = [], [], []
    for gi, elems in enumerate(group_elems):
        s = rs._shard_sz(elems, n)
        mu = _rows(-50, 50, elems, s)
        nu = _rows(0, 50, elems, s)
        count = np.full((n,), 17, np.int32)
        master = _rows(-50, 50, elems, s) if gi == 0 else None
        slots.append(_ShardSlot({"mu": mu, "nu": nu, "count": count},
                                master))
        accum.append(_rows(-9, 9, elems, s))
        if gi == 0:
            w = elems + (-elems) % n
            e = np.zeros((n, w), np.float32)
            e[:, :elems] = rng.randint(-5, 5, size=(n, elems))
            ef.append(e)
        else:
            ef.append(None)
    return DistributedOptState(
        tuple(slots), _ZeroAccum(tuple(accum)), np.asarray(3),
        None, _WireEF(tuple(ef), np.asarray(ef_gen, np.int32)))


def _assert_state_bitwise(a, b):
    import jax
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype and x.shape == y.shape
        assert x.tobytes() == y.tobytes()


@pytest.mark.parametrize("n_old,n_new", [(4, 2), (2, 4), (4, 1), (1, 3)])
def test_reshard_opt_state_geometry(n_old, n_new):
    ge = (10, 7)
    st = _synthetic_state(n_old, ge)
    out = rs.reshard_opt_state(st, ge, n_new)
    for gi, elems in enumerate(ge):
        s = rs._shard_sz(elems, n_new)
        assert np.asarray(out.inner[gi].state["mu"]).shape == (n_new, s)
        assert np.asarray(out.inner[gi].state["count"]).shape == (n_new,)
        # shard rows concat back to the same logical buffer
        np.testing.assert_array_equal(
            np.asarray(out.inner[gi].state["mu"]).reshape(-1)[:elems],
            np.asarray(st.inner[gi].state["mu"]).reshape(-1)[:elems])
    assert np.asarray(out.wire_ef.rows[0]).shape[0] == n_new
    assert out.wire_ef.rows[1] is None


def test_shard_rows_roundtrip_bitwise():
    st = _synthetic_state(4)
    back = rs.reshard_opt_state(rs.reshard_opt_state(st, (10, 7), 1),
                                (10, 7), 4)
    # EF fold is deliberately lossy across a round trip (residual is
    # merged); everything else must round-trip bitwise.
    _assert_state_bitwise(back._replace(wire_ef=None),
                          st._replace(wire_ef=None))


def test_live_reshard_matches_local_restack_bitwise():
    """The scenario-(a) equivalence at the heart of the PR: moving an
    optimizer state through the chunked transport must equal the
    scenario-(c) local restack bit for bit — including the EF fold."""
    ge = (10, 7)
    n_old, n_new = 2, 1
    st = _synthetic_state(n_old, ge)
    expected = rs.reshard_opt_state(st, ge, n_new)

    t = rs.LocalTransport()
    per_old = [rs.opt_state_streams(st, ge, n_old, r)
               for r in range(n_old)]
    specs = per_old[0][0]
    for r in range(n_old):
        rs.reshard_streams(specs, per_old[r][1], n_old, n_new, r, None,
                           t, chunk_bytes=32)
    streams, _ = rs.reshard_streams(specs, None, n_old, n_new, None, 0,
                                    t, chunk_bytes=32, timeout=5.0)
    got = rs.streams_to_opt_state(st, streams, ge, n_new, 0)
    _assert_state_bitwise(got, expected)


def test_merge_rank_streams_grow_matches_restack():
    ge = (10, 7)
    st = _synthetic_state(1, ge)
    expected = rs.reshard_opt_state(st, ge, 2)

    t = rs.LocalTransport()
    specs, data = rs.opt_state_streams(st, ge, 1, 0)
    rs.reshard_streams(specs, data, 1, 2, 0, None, t, chunk_bytes=32)
    per_new, _ = _fetch_all(specs, 1, 2, t, chunk_bytes=32,
                            timeout=5.0)
    merged = rs.merge_rank_streams(specs, per_new, 2)
    got = rs.compat_opt_state_from_streams(st, merged, ge, 2)
    _assert_state_bitwise(got, expected)


def test_plan_meta_roundtrip():
    specs = [rs.StreamSpec("p0", 10, "float32", "shard"),
             rs.StreamSpec("e0", 10, "float32", "perrank"),
             rs.StreamSpec("o0.2", 1, "int32", "replicated")]
    back, n_old = rs.plan_meta_parse(rs.plan_meta_json(specs, 3))
    assert back == specs and n_old == 3


# ---------------------------------------------------------------------------
# wire payload encoding


def test_host_wire_exact_and_cast():
    from horovod_tpu.ops import wire
    x = np.arange(9, dtype=np.float32) / 3
    for w in (None, "none"):
        out = wire.host_decode(wire.host_encode(x, w), np.float32, w)
        assert out.tobytes() == x.tobytes()
    out = wire.host_decode(wire.host_encode(x, "fp16"), np.float32,
                           "fp16")
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, x, rtol=1e-3)
    with pytest.raises(HorovodTpuError, match="cooperative"):
        wire.host_encode(x, "int8")


# ---------------------------------------------------------------------------
# zero3 regroup + scenario (b) decode handoff


def test_zero3_regroup_geometry():
    import jax.numpy as jnp

    from horovod_tpu.parallel.zero3 import zero3_placement
    params = {"w": jnp.zeros((6, 4), jnp.float32),
              "b": jnp.zeros((5,), jnp.float32)}
    pl = zero3_placement(params)
    re2 = pl.regroup(2)
    assert re2.n == 2
    assert re2.group_elems == pl.group_elems
    assert tuple(g.idxs for g in re2.groups) == \
        tuple(g.idxs for g in pl.groups)
    for g in re2.groups:
        assert g.shard_sz * 2 >= sum(g.sizes)


def test_decode_handoff_slices_bitwise():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.serve.handoff import (
        fetch_decode_params, handoff_meta, publish_for_serve,
    )
    rng = np.random.RandomState(11)
    params = {
        "emb": jnp.asarray(rng.uniform(size=(5, 4)), jnp.float32),
        "wi": jnp.asarray(rng.uniform(size=(4, 6)), jnp.float32),
        "wo": jnp.asarray(rng.uniform(size=(6, 4)), jnp.float32),
    }
    pspecs = {"emb": P(), "wi": P(None, "tp"), "wo": P("tp", None)}
    leaf_meta, groups = handoff_meta(params, pspecs)

    # build the zero3 rows the trainer would hold (n_old = 2)
    leaves = jax.tree_util.tree_leaves(params)
    n_old = 2
    rows, ge = [], []
    for idxs, sizes in groups:
        flat = np.concatenate(
            [np.asarray(leaves[i]).reshape(-1) for i in idxs])
        ge.append(flat.size)
        s = rs._shard_sz(flat.size, n_old)
        rows.append(np.pad(flat, (0, n_old * s - flat.size))
                    .reshape(n_old, s))
    ge = tuple(ge)

    t = rs.LocalTransport()
    for r in range(n_old):
        publish_for_serve(rows, ge, n_old, r, t, tag="serve",
                          chunk_bytes=24)
    tp = 2
    for j in range(tp):
        got = fetch_decode_params(params, pspecs, t, tag="serve",
                                  tp=tp, tp_rank=j, chunk_bytes=24,
                                  timeout=5.0)
        exp = {
            "emb": np.asarray(params["emb"]),
            "wi": np.asarray(params["wi"])[:, j * 3:(j + 1) * 3],
            "wo": np.asarray(params["wo"])[j * 3:(j + 1) * 3, :],
        }
        for k in exp:
            assert np.asarray(got[k]).tobytes() == exp[k].tobytes(), k


def test_handoff_drift_raises():
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.serve.handoff import fetch_decode_params
    params = {"w": jnp.zeros((4, 4), jnp.float32)}
    t = rs.LocalTransport()
    t.put("serve/meta", rs.plan_meta_json(
        [rs.StreamSpec("p0", 999, "float32", "shard")], 2))
    with pytest.raises(HorovodTpuError, match="drift"):
        fetch_decode_params(params, {"w": P(None, "tp")}, t,
                            tag="serve", tp=2, tp_rank=0, timeout=2.0)
