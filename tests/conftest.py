"""Test harness: 8 simulated TPU ranks via the CPU host platform.

Mirrors the reference's test strategy (SURVEY.md §4): Horovod runs its
parallel suites under a real 2-process `horovodrun`; here N ranks are N
virtual devices in one process (`--xla_force_host_platform_device_count=8`),
which exercises the identical SPMD collective code paths that run on a pod
slice — better coverage per test than the reference's 2 processes.
"""

import os

# Must happen before jax import anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize pins jax_platforms to the TPU plugin at
# interpreter start; env alone cannot override it, so force CPU here
# before any backend is initialized.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "integration: multi-process integration tests")
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 "
        "smoke pass (-m 'not slow')")


@pytest.fixture(scope="session", autouse=True)
def hvd_init():
    hvd.init()
    yield
    hvd.shutdown()


@pytest.fixture()
def mesh():
    return hvd.global_mesh()
