"""Spark integration tests (reference: test/single/test_spark.py — run()
semantics against a local fake cluster; no JVM needed here because the
barrier-task surface is duck-typed).
"""

import base64
import os
import pickle
import socket
import subprocess
import sys

import pytest

import horovod_tpu.spark as hvd_spark
from horovod_tpu.common.exceptions import HorovodTpuError

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# run() orchestration against an in-process fake pyspark
# ---------------------------------------------------------------------------

class _FakeTaskInfo:
    def __init__(self, address):
        self.address = address


class _FakeCtx:
    def __init__(self, rank, size):
        self._rank, self._size = rank, size

    def partitionId(self):  # noqa: N802
        return self._rank

    def getTaskInfos(self):  # noqa: N802
        return [_FakeTaskInfo("127.0.0.1:0")] * self._size

    def barrier(self):
        pass  # sequential fake: nothing to synchronize


class _FakeRDD:
    def __init__(self, n):
        self._n = n

    def barrier(self):
        return self

    def mapPartitionsWithIndex(self, mapper):  # noqa: N802
        self._mapper = mapper
        return self

    def collect(self):
        rows = []
        saved = dict(os.environ)
        try:
            for r in range(self._n):
                rows.extend(self._mapper(r, iter([]), ctx=_FakeCtx(r, self._n)))
        finally:
            os.environ.clear()
            os.environ.update(saved)
        return rows


class _FakeConf:
    def get(self, key, default=None):
        return "127.0.0.1" if key == "spark.driver.host" else default


class _FakeSparkContext:
    defaultParallelism = 2

    def getConf(self):
        return _FakeConf()

    def parallelize(self, seq, n):
        return _FakeRDD(n)


def _fn_env_echo(tag):
    return (tag, os.environ["HOROVOD_RANK"], os.environ["HOROVOD_SIZE"])


@pytest.fixture()
def fake_pyspark(monkeypatch):
    import types

    mod = types.ModuleType("pyspark")
    mod.SparkContext = types.SimpleNamespace(
        _active_spark_context=_FakeSparkContext())
    monkeypatch.setitem(sys.modules, "pyspark", mod)
    return mod


class TestSparkRun:
    def test_run_returns_results_by_rank(self, fake_pyspark):
        out = hvd_spark.run(_fn_env_echo, args=("t",), num_proc=3)
        assert out == [("t", "0", "3"), ("t", "1", "3"), ("t", "2", "3")]

    def test_run_defaults_to_parallelism(self, fake_pyspark):
        out = hvd_spark.run(_fn_env_echo, args=("d",))
        assert len(out) == 2  # defaultParallelism

    def test_run_without_pyspark_raises_import_error(self, monkeypatch):
        monkeypatch.setitem(sys.modules, "pyspark", None)
        with pytest.raises(ImportError, match="requires pyspark"):
            hvd_spark.run(_fn_env_echo)

    def test_run_without_context_raises(self, fake_pyspark):
        fake_pyspark.SparkContext._active_spark_context = None
        with pytest.raises(HorovodTpuError, match="No active SparkContext"):
            hvd_spark.run(_fn_env_echo)

    def test_run_elastic_shrinks_on_failure(self, fake_pyspark,
                                            monkeypatch):
        calls = []

        def flaky_run(fn, args=(), kwargs=None, num_proc=None, **kw):
            calls.append(num_proc)
            if num_proc > 2:
                raise RuntimeError("stage failed")
            return ["ok"] * num_proc

        monkeypatch.setattr(hvd_spark, "run", flaky_run)
        out = hvd_spark.run_elastic(_fn_env_echo, num_proc=4, min_np=2)
        assert out == ["ok", "ok"]
        assert calls == [4, 3, 2]


# ---------------------------------------------------------------------------
# Real 2-process barrier stage: collectives through the mapper
# ---------------------------------------------------------------------------

@pytest.mark.integration
class TestSparkBarrierCollectives:
    def test_two_task_barrier_allreduce(self):
        from horovod_tpu.runner.rendezvous import RendezvousServer

        server = RendezvousServer()
        port = server.start()
        with socket.socket() as s:
            s.bind(("", 0))
            coord_port = s.getsockname()[1]
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.update({
            "TEST_RDV_ADDR": "127.0.0.1",
            "TEST_RDV_PORT": str(port),
            "TEST_RDV_SECRET": server.secret,
            "TEST_COORD_PORT": str(coord_port),
        })
        script = os.path.join(REPO_ROOT, "tests", "data",
                              "spark_task_main.py")
        procs = [
            subprocess.Popen([sys.executable, script, str(r), "2"], env=env)
            for r in range(2)
        ]
        try:
            for p in procs:
                assert p.wait(timeout=240) == 0
            kv = server.kv()
            results = {}
            for r in range(2):
                raw = kv.get(f"spark/result/{r}")
                assert raw is not None, f"no result from task {r}"
                results[r] = pickle.loads(base64.b64decode(raw))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            server.stop()
        # sum over ranks of (rank+1)*10 = 30 on both tasks.
        assert results[0] == [30.0, 30.0]
        assert results[1] == [30.0, 30.0]
