"""Collective correctness: op x dtype x process-set vs a local NumPy
reference (mirrors the reference's test_tensorflow.py / test_torch.py
pattern, SURVEY.md §4: "every collective × dtype × device combination
asserts numerical equality vs a local reference computation").

Each virtual device is one Horovod rank; `PerRank` supplies distinct
per-rank contributions the way `horovodrun -np 8` would.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import PerRank

N = 8

FLOAT_DTYPES = [np.float32, np.float16, "bfloat16"]
INT_DTYPES = [np.int32, np.uint8]


def per_rank_data(shape, dtype, seed=0):
    rng = np.random.RandomState(seed)
    vals = []
    for r in range(N):
        if dtype in (np.uint8,):
            v = rng.randint(0, 8, size=shape).astype(dtype)
        elif dtype in (np.int32,):
            v = rng.randint(-10, 10, size=shape).astype(dtype)
        else:
            v = rng.uniform(-1, 1, size=shape).astype(dtype)
        vals.append(v)
    return vals


# ---------------------------------------------------------------------------
# Allreduce
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", FLOAT_DTYPES)
@pytest.mark.parametrize("shape", [(4,), (3, 5), (2, 3, 4)])
def test_allreduce_average(dtype, shape):
    vals = per_rank_data(shape, dtype)
    out = hvd.allreduce(PerRank(vals), op=hvd.Average)
    expected = np.mean(np.stack([np.asarray(v, np.float32) for v in vals]),
                       axis=0)
    rtol = 1e-5 if dtype == np.float32 else 5e-2
    np.testing.assert_allclose(np.asarray(out, np.float32), expected,
                               rtol=rtol, atol=rtol)
    assert str(out.dtype) == str(jnp.dtype(dtype))


@pytest.mark.parametrize("dtype", FLOAT_DTYPES + INT_DTYPES)
def test_allreduce_sum(dtype):
    vals = per_rank_data((6,), dtype)
    out = hvd.allreduce(PerRank(vals), op=hvd.Sum)
    expected = np.sum(np.stack([np.asarray(v, np.float64) for v in vals]),
                      axis=0).astype(dtype)
    rtol = 1e-5 if dtype in (np.float32, np.int32, np.uint8) else 5e-2
    np.testing.assert_allclose(
        np.asarray(out, np.float64), np.asarray(expected, np.float64),
        rtol=rtol, atol=rtol,
    )


@pytest.mark.parametrize("op,npop", [
    (hvd.Min, np.min), (hvd.Max, np.max), (hvd.Product, np.prod),
])
def test_allreduce_minmaxprod(op, npop):
    vals = per_rank_data((5,), np.float32)
    out = hvd.allreduce(PerRank(vals), op=op)
    expected = npop(np.stack(vals), axis=0)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_allreduce_prescale_postscale():
    vals = per_rank_data((4,), np.float32)
    out = hvd.allreduce(PerRank(vals), op=hvd.Sum,
                        prescale_factor=0.5, postscale_factor=2.0)
    expected = 2.0 * np.sum(0.5 * np.stack(vals), axis=0)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_allreduce_same_value_all_ranks():
    # Plain-array input: every rank contributes the same tensor.
    x = np.arange(4, dtype=np.float32)
    out = hvd.allreduce(x, op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out), x * N)
    out = hvd.allreduce(x, op=hvd.Average)
    np.testing.assert_allclose(np.asarray(out), x)


def test_allreduce_process_set():
    ps = hvd.add_process_set([0, 1, 2, 3])
    try:
        vals = per_rank_data((4,), np.float32)[:4]
        out = hvd.allreduce(PerRank(vals), op=hvd.Sum, process_set=ps)
        np.testing.assert_allclose(
            np.asarray(out), np.sum(np.stack(vals), axis=0), rtol=1e-5
        )
    finally:
        hvd.remove_process_set(ps)


def test_grouped_allreduce():
    a = per_rank_data((3,), np.float32, seed=1)
    b = per_rank_data((2, 2), np.float32, seed=2)
    c = per_rank_data((4,), np.int32, seed=3)
    outs = hvd.grouped_allreduce(
        [PerRank(a), PerRank(b), PerRank(c)], op=hvd.Sum
    )
    np.testing.assert_allclose(np.asarray(outs[0]),
                               np.sum(np.stack(a), 0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]),
                               np.sum(np.stack(b), 0), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(outs[2]), np.sum(np.stack(c), 0))


# ---------------------------------------------------------------------------
# In-jit (shard_map) collectives — the money path
# ---------------------------------------------------------------------------

def _shard_mapped(fn, mesh, n_in=1):
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    return shard_map(
        fn, mesh=mesh,
        in_specs=tuple(P(hvd.GLOBAL_AXIS) for _ in range(n_in)),
        out_specs=P(),
        check_vma=False,
    )


def test_allreduce_inside_shard_map(mesh):
    vals = per_rank_data((4,), np.float32)
    stacked = jnp.stack(vals)

    def f(x):
        return hvd.allreduce(x[0], op=hvd.Average)

    out = jax.jit(_shard_mapped(f, mesh))(stacked)
    np.testing.assert_allclose(
        np.asarray(out), np.mean(np.stack(vals), 0), rtol=1e-5
    )


def test_grouped_allreduce_inside_shard_map(mesh):
    a = jnp.stack(per_rank_data((3,), np.float32, seed=5))
    b = jnp.stack(per_rank_data((2,), np.float32, seed=6))

    def f(x, y):
        outs = hvd.grouped_allreduce([x[0], y[0]], op=hvd.Sum)
        return tuple(outs)

    oa, ob = jax.jit(_shard_mapped(f, mesh, n_in=2))(a, b)
    np.testing.assert_allclose(np.asarray(oa), np.sum(np.asarray(a), 0),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ob), np.sum(np.asarray(b), 0),
                               rtol=1e-5)


def test_minmax_inside_shard_map(mesh):
    vals = jnp.stack(per_rank_data((4,), np.float32))

    def f(x):
        return hvd.allreduce(x[0], op=hvd.Min), \
            hvd.allreduce(x[0], op=hvd.Max)

    mn, mx = jax.jit(_shard_mapped(f, mesh))(vals)
    np.testing.assert_allclose(np.asarray(mn), np.min(np.asarray(vals), 0))
    np.testing.assert_allclose(np.asarray(mx), np.max(np.asarray(vals), 0))


# ---------------------------------------------------------------------------
# Allgather
# ---------------------------------------------------------------------------

def test_allgather_uniform():
    vals = per_rank_data((3, 2), np.float32)
    out = hvd.allgather(PerRank(vals))
    np.testing.assert_allclose(np.asarray(out), np.concatenate(vals, 0),
                               rtol=1e-5)


def test_allgather_ragged():
    rng = np.random.RandomState(7)
    vals = [rng.uniform(size=(r + 1, 2)).astype(np.float32)
            for r in range(N)]
    out = hvd.allgather(PerRank(vals))
    np.testing.assert_allclose(np.asarray(out), np.concatenate(vals, 0),
                               rtol=1e-5)


def test_allgather_same_input():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = hvd.allgather(x)
    np.testing.assert_allclose(np.asarray(out), np.tile(x, (N, 1)))


def test_allgather_inside_shard_map(mesh):
    vals = jnp.stack(per_rank_data((2,), np.float32))

    def f(x):
        return hvd.allgather(x[0])

    out = jax.jit(_shard_mapped(f, mesh))(vals)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(vals).reshape(-1), rtol=1e-5)


# ---------------------------------------------------------------------------
# Broadcast
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(root):
    vals = per_rank_data((4,), np.float32)
    out = hvd.broadcast(PerRank(vals), root_rank=root)
    np.testing.assert_allclose(np.asarray(out), vals[root], rtol=1e-5)


def test_broadcast_inside_shard_map(mesh):
    vals = jnp.stack(per_rank_data((4,), np.float32))

    def f(x):
        return hvd.broadcast(x[0], root_rank=5)

    out = jax.jit(_shard_mapped(f, mesh))(vals)
    np.testing.assert_allclose(np.asarray(out), np.asarray(vals)[5],
                               rtol=1e-5)


def test_broadcast_parameters():
    params = {
        "w": PerRank(per_rank_data((3, 3), np.float32, seed=11)),
        "b": PerRank(per_rank_data((3,), np.float32, seed=12)),
    }
    # broadcast_parameters works on pytrees of plain arrays; use rank-0
    # values directly for the pytree form.
    tree = {"w": params["w"].values[0], "b": params["b"].values[0]}
    out = hvd.broadcast_parameters(tree, root_rank=0)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.asarray(tree["w"]), rtol=1e-5)


def test_broadcast_object():
    obj = {"epoch": 3, "lr": 0.1, "name": "resnet"}
    out = hvd.broadcast_object(obj, root_rank=0)
    assert out == obj


def test_allgather_object():
    outs = hvd.allgather_object({"rank": hvd.rank()})
    assert len(outs) == N
    assert outs[0] == {"rank": 0}


# ---------------------------------------------------------------------------
# Alltoall
# ---------------------------------------------------------------------------

def test_alltoall_even():
    # rank r sends chunk j to rank j; all chunks length 2.
    vals = [np.arange(N * 2, dtype=np.float32) + 100 * r for r in range(N)]
    out = hvd.alltoall(PerRank(vals))
    assert isinstance(out, PerRank)
    for j in range(N):
        expected = np.concatenate(
            [vals[r][2 * j: 2 * j + 2] for r in range(N)]
        )
        np.testing.assert_allclose(np.asarray(out.values[j]), expected)


def test_alltoall_splits():
    # rank r sends r+1 elements to each destination? use varying splits
    rng = np.random.RandomState(3)
    splits = [np.array([(r + d) % 3 + 1 for d in range(N)], np.int32)
              for r in range(N)]
    vals = [rng.uniform(size=(int(np.sum(s)),)).astype(np.float32)
            for s in splits]
    out, rsplits = hvd.alltoall(PerRank(vals), splits=PerRank(splits))
    for j in range(N):
        pieces = []
        for r in range(N):
            off = int(np.sum(splits[r][:j]))
            pieces.append(vals[r][off: off + int(splits[r][j])])
        expected = np.concatenate(pieces)
        np.testing.assert_allclose(np.asarray(out.values[j]), expected,
                                   rtol=1e-5)
        np.testing.assert_array_equal(
            np.asarray(rsplits.values[j]),
            np.array([splits[r][j] for r in range(N)], np.int32),
        )


def test_alltoall_inside_shard_map(mesh):
    vals = jnp.stack(
        [jnp.arange(N, dtype=jnp.float32) + 10 * r for r in range(N)]
    )

    def f(x):
        return hvd.allgather(hvd.alltoall(x[0]))

    out = jax.jit(_shard_mapped(f, mesh))(vals)
    got = np.asarray(out).reshape(N, N)
    np.testing.assert_allclose(got, np.asarray(vals).T)


# ---------------------------------------------------------------------------
# Reducescatter / barrier / join / async
# ---------------------------------------------------------------------------

def test_reducescatter():
    vals = per_rank_data((N * 2,), np.float32)
    out = hvd.reducescatter(PerRank(vals), op=hvd.Sum)
    total = np.sum(np.stack(vals), 0)
    for j in range(N):
        np.testing.assert_allclose(np.asarray(out.values[j]),
                                   total[2 * j: 2 * j + 2], rtol=1e-5)


def test_barrier():
    hvd.barrier()  # must not hang or raise


def test_join():
    assert hvd.join() == N - 1


def test_async_allreduce():
    vals = per_rank_data((4,), np.float32)
    handle = hvd.allreduce_async(PerRank(vals), op=hvd.Sum)
    out = hvd.synchronize(handle)
    np.testing.assert_allclose(np.asarray(out), np.sum(np.stack(vals), 0),
                               rtol=1e-5)


def test_poll_then_synchronize():
    handle = hvd.allreduce_async(np.ones((2,), np.float32), op=hvd.Sum)
    # poll may be True or False; must not raise, then synchronize works.
    hvd.poll(handle)
    out = hvd.synchronize(handle)
    np.testing.assert_allclose(np.asarray(out), np.full((2,), float(N)))


# ---------------------------------------------------------------------------
# Compression
# ---------------------------------------------------------------------------

def test_fp16_compression_roundtrip():
    from horovod_tpu import Compression

    x = jnp.asarray(np.random.RandomState(0).uniform(size=(8,)), jnp.float32)
    c, ctx = Compression.fp16.compress(x)
    assert c.dtype == jnp.float16
    d = Compression.fp16.decompress(c, ctx)
    assert d.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(d), np.asarray(x), rtol=1e-3)


def test_one_byte_wire_formats_raise_on_cast_path():
    # int8/fp8 are cooperative ring formats (quantized ring allreduce,
    # f32 accumulate per hop) — a pre-collective cast would mis-sum
    # (e4m3 saturates at ±448), so the cast path refuses loudly.
    from horovod_tpu import Compression

    for comp in (Compression.int8, Compression.fp8_e4m3,
                 Compression.fp8_e5m2):
        with pytest.raises(NotImplementedError, match="in-jit"):
            comp.compress(jnp.ones((4,)))
    with pytest.raises(ValueError, match="in-jit path"):
        hvd.allreduce_gradients({"g": jnp.ones((4,))},
                                compression=Compression.fp8_e4m3)


# ---------------------------------------------------------------------------
# Regression tests for review findings
# ---------------------------------------------------------------------------

def test_alltoall_plain_2d_tensor():
    # Even-split eager alltoall must preserve trailing dims (regression:
    # reshape used x.shape[3:] and crashed on rank>=2 tensors).
    x = np.arange(N * 3 * 2, dtype=np.float32).reshape(N * 3, 2)
    out = hvd.alltoall(x)
    # All ranks send the same tensor → each rank receives N copies of its
    # chunk; this process's view is rank 0's result.
    expected = np.concatenate([x[0:3] for _ in range(N)], axis=0)
    assert out.shape == (N * 3, 2)
    np.testing.assert_allclose(np.asarray(out), expected)


@pytest.mark.parametrize("root", [0, 3])
def test_broadcast_object_nonzero_root(root):
    # Regression: root ownership must follow the rank-per-chip model, not
    # just the process's first device.
    obj = {"v": 42}
    out = hvd.broadcast_object(obj, root_rank=root)
    assert out == obj


def test_reducescatter_rejects_minmax():
    from horovod_tpu.common.exceptions import HorovodTpuError

    with pytest.raises(HorovodTpuError):
        hvd.reducescatter(np.ones((N * 2,), np.float32), op=hvd.Max)


def test_alltoall_splits_inside_jit_raises(mesh):
    from horovod_tpu.common.exceptions import HorovodTpuError

    vals = jnp.stack([jnp.arange(N, dtype=jnp.float32)] * N)

    def f(x):
        return hvd.alltoall(x[0], splits=[1] * N)

    with pytest.raises(HorovodTpuError):
        jax.jit(_shard_mapped(f, mesh))(vals)


# ---------------------------------------------------------------------------
# Process sets inside jit (reference: process_set.cc semantics apply to
# every op; the tracer path must honor the subset or refuse loudly)
# ---------------------------------------------------------------------------

def test_allreduce_process_set_inside_shard_map(mesh):
    ps = hvd.add_process_set([0, 2, 4, 6])
    try:
        vals = per_rank_data((4,), np.float32)
        stacked = jnp.stack(vals)

        def f(x):
            return hvd.allreduce(x[0], op=hvd.Average, process_set=ps)

        out = jax.jit(_shard_mapped(f, mesh))(stacked)
        expected = np.mean(np.stack([vals[r] for r in ps.ranks]), 0)
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)
    finally:
        hvd.remove_process_set(ps)


def test_allreduce_process_set_sum_min_inside_shard_map(mesh):
    ps = hvd.add_process_set([1, 3, 5])
    try:
        vals = per_rank_data((3,), np.float32)
        stacked = jnp.stack(vals)

        def f(x):
            return (hvd.allreduce(x[0], op=hvd.Sum, process_set=ps),
                    hvd.allreduce(x[0], op=hvd.Min, process_set=ps))

        s, mn = jax.jit(_shard_mapped(f, mesh))(stacked)
        sub = np.stack([vals[r] for r in ps.ranks])
        np.testing.assert_allclose(np.asarray(s), sub.sum(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(mn), sub.min(0), rtol=1e-5)
    finally:
        hvd.remove_process_set(ps)


def test_broadcast_process_set_inside_shard_map(mesh):
    ps = hvd.add_process_set([1, 3])
    try:
        vals = per_rank_data((2,), np.float32)
        stacked = jnp.stack(vals)

        def f(x):
            # root_rank is set-relative: 1 -> global rank 3.
            return hvd.broadcast(x[0], root_rank=1, process_set=ps)

        out = jax.jit(_shard_mapped(f, mesh))(stacked)
        np.testing.assert_allclose(np.asarray(out), vals[3], rtol=1e-5)
    finally:
        hvd.remove_process_set(ps)


def _shard_mapped_per_rank(fn, mesh, n_in=1):
    """Like _shard_mapped but keeps PER-RANK outputs (row r = rank r's
    view) — required for set-scoped gather-type ops, where member and
    filler-group ranks legitimately see different results."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    return shard_map(
        fn, mesh=mesh,
        in_specs=tuple(P(hvd.GLOBAL_AXIS) for _ in range(n_in)),
        out_specs=P(hvd.GLOBAL_AXIS),
        check_vma=False,
    )


def test_allgather_process_set_inside_shard_map(mesh):
    # axis_index_groups path (r4 verdict task 6): members gather the
    # subset in set-rank order; filler-group ranks' outputs are
    # meaningless by contract (non-members never call the op upstream).
    ps = hvd.add_process_set([0, 2, 4, 6])
    try:
        vals = per_rank_data((3,), np.float32)
        stacked = jnp.stack(vals)

        def f(x):
            return hvd.allgather(x[0], process_set=ps)[None]

        out = np.asarray(jax.jit(_shard_mapped_per_rank(f, mesh))(stacked))
        expected = np.concatenate([vals[r] for r in ps.ranks])
        for r in ps.ranks:
            np.testing.assert_allclose(out[r], expected, rtol=1e-5)
    finally:
        hvd.remove_process_set(ps)


def test_reducescatter_process_set_inside_shard_map(mesh):
    ps = hvd.add_process_set([0, 2, 4, 6])
    try:
        vals = per_rank_data((8,), np.float32)
        stacked = jnp.stack(vals)

        def f(x):
            return (hvd.reducescatter(x[0], op=hvd.Sum,
                                      process_set=ps)[None],
                    hvd.reducescatter(x[0], op=hvd.Average,
                                      process_set=ps)[None])

        s, avg = jax.jit(_shard_mapped_per_rank(f, mesh))(stacked)
        s, avg = np.asarray(s), np.asarray(avg)
        total = np.sum(np.stack([vals[r] for r in ps.ranks]), 0)
        for i, r in enumerate(ps.ranks):
            np.testing.assert_allclose(s[r], total[2 * i: 2 * i + 2],
                                       rtol=1e-5)
            np.testing.assert_allclose(
                avg[r], total[2 * i: 2 * i + 2] / len(ps.ranks),
                rtol=1e-5)
    finally:
        hvd.remove_process_set(ps)


def test_alltoall_process_set_inside_shard_map(mesh):
    ps = hvd.add_process_set([1, 3, 5, 7])
    try:
        vals = per_rank_data((4,), np.float32)
        stacked = jnp.stack(vals)

        def f(x):
            return hvd.alltoall(x[0], process_set=ps)[None]

        out = np.asarray(jax.jit(_shard_mapped_per_rank(f, mesh))(stacked))
        for j, r in enumerate(ps.ranks):
            expected = np.asarray([vals[m][j] for m in ps.ranks])
            np.testing.assert_allclose(out[r], expected, rtol=1e-5)
    finally:
        hvd.remove_process_set(ps)


def test_gather_type_process_set_non_divisible_raises(mesh):
    # |set| = 3 cannot partition an 8-rank axis into equal groups — the
    # one case XLA truly cannot express stays a loud refusal.
    from horovod_tpu.common.exceptions import HorovodTpuError

    ps = hvd.add_process_set([0, 1, 2])
    try:
        vals = jnp.stack([jnp.arange(N, dtype=jnp.float32)] * N)

        def g(x):
            return hvd.allgather(x[0], process_set=ps)

        with pytest.raises(HorovodTpuError, match="divide the axis size"):
            jax.jit(_shard_mapped(g, mesh))(vals)

        def rs(x):
            return hvd.reducescatter(x[0], process_set=ps)

        with pytest.raises(HorovodTpuError, match="divide the axis size"):
            jax.jit(_shard_mapped(rs, mesh))(vals)
    finally:
        hvd.remove_process_set(ps)


# ---------------------------------------------------------------------------
# Device-resident eager path (reference: fusion_buffer_manager.cc keeps
# payloads in device memory; the eager API must not round-trip via host)
# ---------------------------------------------------------------------------

def test_eager_allreduce_no_device_to_host():
    x = jnp.arange(1024, dtype=jnp.float32)  # device-resident input
    with jax.transfer_guard_device_to_host("disallow"):
        out = hvd.allreduce(x, op=hvd.Sum)
        out2 = hvd.allreduce(PerRank([x + r for r in range(N)]), op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out),
                               np.arange(1024, dtype=np.float32) * N)
    np.testing.assert_allclose(
        np.asarray(out2),
        np.arange(1024, dtype=np.float32) * N + sum(range(N)))


def test_eager_broadcast_no_device_to_host():
    x = jnp.full((16,), float(hvd.rank()))
    with jax.transfer_guard_device_to_host("disallow"):
        out = hvd.broadcast(x, root_rank=0)
    assert np.asarray(out).shape == (16,)


def test_reducescatter_two_shapes_same_cache():
    # Regression: the program cache must not bake the first call's dim0.
    out1 = hvd.reducescatter(np.ones((N * 2,), np.float32), op=hvd.Sum)
    out2 = hvd.reducescatter(np.ones((N * 4,), np.float32), op=hvd.Sum)
    assert np.asarray(out1).shape == (2,)
    assert np.asarray(out2).shape == (4,)


def test_alltoall_splits_must_sum_to_dim0():
    from horovod_tpu.common.exceptions import HorovodTpuError

    with pytest.raises(HorovodTpuError, match="sum to dim0"):
        hvd.alltoall(np.arange(3, dtype=np.float32),
                     splits=[2] + [0] * (N - 2) + [3])
    with pytest.raises(HorovodTpuError, match="one entry per rank"):
        hvd.alltoall(np.arange(3, dtype=np.float32), splits=[1, 2])


def test_broadcast_process_set_root_out_of_range_in_jit(mesh):
    from horovod_tpu.common.exceptions import HorovodTpuError

    ps = hvd.add_process_set([1, 3])
    try:
        vals = jnp.stack([jnp.full((2,), float(r)) for r in range(N)])

        def f(x):
            return hvd.broadcast(x[0], root_rank=-1, process_set=ps)

        with pytest.raises(HorovodTpuError, match="out of range"):
            jax.jit(_shard_mapped(f, mesh))(vals)
    finally:
        hvd.remove_process_set(ps)


def test_reducescatter_and_grouped_async():
    h = hvd.reducescatter_async(np.ones((N * 2,), np.float32), op=hvd.Sum)
    out = hvd.synchronize(h)
    np.testing.assert_allclose(np.asarray(out), np.full((2,), float(N)))
    h2 = hvd.grouped_allreduce_async(
        [np.ones((3,), np.float32), np.full((2,), 2.0, np.float32)],
        op=hvd.Sum)
    outs = hvd.synchronize(h2)
    np.testing.assert_allclose(np.asarray(outs[0]), np.full((3,), float(N)))
    np.testing.assert_allclose(np.asarray(outs[1]),
                               np.full((2,), 2.0 * N))


# ---------------------------------------------------------------------------
# Reducescatter padding + fused grouped_reducescatter
# ---------------------------------------------------------------------------


def test_reducescatter_pads_non_divisible_eager():
    """dim0=10 over 8 ranks: the eager path pads to 16, scatters 2 rows
    per rank, and trims — ranks 0-4 get 2 rows, rank 5 gets 0-2, the
    tail ranks get empty slices (ceil-chunk ownership)."""
    vals = [np.full((10, 3), float(r + 1), np.float32) for r in range(N)]
    out = hvd.reducescatter(PerRank(vals), op=hvd.Sum)
    total = np.sum(np.stack(vals), 0)
    chunk = 2  # ceil(10/8)
    off = 0
    for j, row in enumerate(out.values):
        keep = max(0, min(10 - j * chunk, chunk))
        assert np.asarray(row).shape == (keep, 3)
        np.testing.assert_allclose(np.asarray(row),
                                   total[off: off + keep], rtol=1e-5)
        off += keep
    assert off == 10


def test_grouped_reducescatter_eager_fused():
    """Mixed non-divisible shapes and mixed dtypes ride ONE compiled
    program per call; results match per-tensor reducescatter."""
    rng = np.random.RandomState(3)
    f32 = [[rng.randn(10, 3).astype(np.float32) for _ in range(2)]
           for _ in range(N)]
    i32 = [[rng.randint(-9, 9, size=(5,)).astype(np.int32)]
           for _ in range(N)]
    tensors = [PerRank([f32[r][0] for r in range(N)]),
               PerRank([i32[r][0] for r in range(N)]),
               PerRank([f32[r][1] for r in range(N)])]
    outs = hvd.grouped_reducescatter(tensors, op=hvd.Sum)
    singles = [hvd.reducescatter(t, op=hvd.Sum) for t in tensors]
    for got, ref in zip(outs, singles):
        for a, b in zip(got.values, ref.values):
            np.testing.assert_allclose(np.asarray(a, np.float64),
                                       np.asarray(b, np.float64),
                                       rtol=1e-5)


def test_grouped_reducescatter_in_jit_matches_per_tensor(mesh):
    vals_a = per_rank_data((N * 2, 3), np.float32, seed=5)
    vals_b = per_rank_data((N,), np.float32, seed=6)

    def grouped(a, b):
        outs = hvd.grouped_reducescatter([a[0], b[0]], op=hvd.Average)
        return outs[0], outs[1]

    def single(a, b):
        return (hvd.reducescatter(a[0], op=hvd.Average),
                hvd.reducescatter(b[0], op=hvd.Average))

    ga, gb = jax.jit(_shard_mapped_per_rank(grouped, mesh, n_in=2))(
        jnp.stack(vals_a), jnp.stack(vals_b))
    sa, sb = jax.jit(_shard_mapped_per_rank(single, mesh, n_in=2))(
        jnp.stack(vals_a), jnp.stack(vals_b))
    np.testing.assert_array_equal(np.asarray(ga), np.asarray(sa))
    np.testing.assert_array_equal(np.asarray(gb), np.asarray(sb))


def test_grouped_reducescatter_in_jit_rejects_non_divisible(mesh):
    from horovod_tpu.common.exceptions import HorovodTpuError

    vals = per_rank_data((10,), np.float32)

    def f(x):
        return hvd.grouped_reducescatter([x[0]], op=hvd.Sum)[0]

    with pytest.raises(HorovodTpuError, match="divisible"):
        jax.jit(_shard_mapped(f, mesh))(jnp.stack(vals))


def test_grouped_reducescatter_rejects_minmax():
    from horovod_tpu.common.exceptions import HorovodTpuError

    with pytest.raises(HorovodTpuError):
        hvd.grouped_reducescatter(
            [np.ones((N * 2,), np.float32)], op=hvd.Max)


# ---------------------------------------------------------------------------
# Wire-format kwargs on the grouped collectives (r6, ops/wire.py)
# ---------------------------------------------------------------------------


def test_grouped_reducescatter_wire_int8_close_to_exact(mesh):
    vals = per_rank_data((N * 32,), np.float32, seed=11)

    def wired(a):
        return hvd.grouped_reducescatter(
            [a[0]], op=hvd.Average, wire="int8")[0]

    def exact(a):
        return hvd.grouped_reducescatter([a[0]], op=hvd.Average)[0]

    got = np.asarray(jax.jit(_shard_mapped_per_rank(wired, mesh))(
        jnp.stack(vals)))
    ref = np.asarray(jax.jit(_shard_mapped_per_rank(exact, mesh))(
        jnp.stack(vals)))
    assert got.shape == ref.shape
    assert np.abs(got - ref).max() < np.abs(np.stack(vals)).max() / 10


def test_grouped_reducescatter_wire_bf16_cast(mesh):
    vals = per_rank_data((N * 4, 3), np.float32, seed=12)

    def wired(a):
        return hvd.grouped_reducescatter(
            [a[0]], op=hvd.Average, wire="bf16")[0]

    got = np.asarray(jax.jit(_shard_mapped_per_rank(wired, mesh))(
        jnp.stack(vals)))
    ref = np.mean(np.stack(vals), 0)
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-2)
    assert got.dtype == np.float32


def test_grouped_allgather_wire_int8(mesh):
    vals = per_rank_data((4, 3), np.float32, seed=13)

    def wired(a):
        return hvd.grouped_allgather([a[0]], wire="int8")[0]

    got = np.asarray(jax.jit(_shard_mapped(wired, mesh))(
        jnp.stack(vals)))
    exact = np.concatenate(vals, axis=0)
    assert got.shape == exact.shape
    # one encode per shard, no accumulation: tight blockwise bound
    assert np.abs(got - exact).max() < np.abs(exact).max() / 100


def test_grouped_allgather_wire_int_dtype_stays_exact(mesh):
    vals = per_rank_data((4,), np.int32, seed=14)

    def wired(a):
        return hvd.grouped_allgather([a[0]], wire="int8")[0]

    got = np.asarray(jax.jit(_shard_mapped(wired, mesh))(
        jnp.stack(vals)))
    np.testing.assert_array_equal(got, np.concatenate(vals))


def test_grouped_wire_eager_raises():
    from horovod_tpu.common.exceptions import HorovodTpuError

    with pytest.raises(HorovodTpuError, match="in-jit only"):
        hvd.grouped_reducescatter(
            [np.ones((N * 2,), np.float32)], op=hvd.Sum, wire="int8")
    with pytest.raises(HorovodTpuError, match="in-jit only"):
        hvd.grouped_allgather([np.ones((4,), np.float32)], wire="int8")


def test_grouped_wire_unknown_raises():
    from horovod_tpu.common.exceptions import HorovodTpuError

    with pytest.raises(HorovodTpuError, match="unknown wire format"):
        hvd.grouped_reducescatter(
            [np.ones((N * 2,), np.float32)], op=hvd.Sum, wire="int9")


# ---------------------------------------------------------------------------
# Bucket-order permutation invariance (r6 wire policy)
# ---------------------------------------------------------------------------


def _bucketed_reduce(mesh, leaves, order, compression=None, policy=None,
                     monkeypatch=None):
    import os
    if policy is not None:
        os.environ["HOROVOD_WIRE_POLICY"] = policy
    else:
        os.environ.pop("HOROVOD_WIRE_POLICY", None)
    kw = {}
    if compression is not None:
        kw["compression"] = compression

    def f(*xs):
        outs = hvd.allreduce_gradients(
            [x[0] for x in xs], axis_name=hvd.GLOBAL_AXIS,
            fusion_threshold_bytes=512, bucket_order=order, **kw)
        return tuple(outs)

    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    sm = jax.jit(shard_map(
        f, mesh=mesh, in_specs=(P(hvd.GLOBAL_AXIS),) * len(leaves),
        out_specs=tuple(P() for _ in leaves), check_vma=False))
    try:
        return [np.asarray(o) for o in sm(*leaves)]
    finally:
        os.environ.pop("HOROVOD_WIRE_POLICY", None)


def _order_test_leaves():
    rng = np.random.RandomState(15)
    return [jnp.asarray(rng.randn(N, n).astype(np.float32))
            for n in (256, 64, 192, 32)]


def test_bucket_order_bitwise_invariant_exact_wire(mesh):
    leaves = _order_test_leaves()
    fwd = _bucketed_reduce(mesh, leaves, "forward")
    rev = _bucketed_reduce(mesh, leaves, "reverse")
    for a, b in zip(fwd, rev):
        np.testing.assert_array_equal(a, b)


def test_bucket_order_agrees_to_wire_tolerance_quantized(mesh):
    # Different orders shift the block-scale boundaries inside the
    # fused flat buffers, so int8/int4 results differ — but only
    # within the quantization tolerance of the wire.
    leaves = _order_test_leaves()
    exact = [np.mean(np.asarray(l), axis=0) for l in leaves]
    for comp, tol_div in ((hvd.Compression.int8, 50),
                          (hvd.Compression.int4, 3)):
        fwd = _bucketed_reduce(mesh, leaves, "forward",
                               compression=comp)
        rev = _bucketed_reduce(mesh, leaves, "reverse",
                               compression=comp)
        scale = max(np.abs(e).max() for e in exact)
        for a, b, e in zip(fwd, rev, exact):
            tol = N * scale / tol_div
            assert np.abs(a - e).max() < tol
            assert np.abs(b - e).max() < tol


def test_bucket_order_agrees_under_wire_policy(mesh):
    leaves = _order_test_leaves()
    exact = [np.mean(np.asarray(l), axis=0) for l in leaves]
    fwd = _bucketed_reduce(mesh, leaves, "forward",
                           policy="big=int8,small=none,threshold=512")
    rev = _bucketed_reduce(mesh, leaves, "reverse",
                           policy="big=int8,small=none,threshold=512")
    scale = max(np.abs(e).max() for e in exact)
    for a, b, e in zip(fwd, rev, exact):
        assert np.abs(a - e).max() < N * scale / 50
        assert np.abs(b - e).max() < N * scale / 50


# ---------------------------------------------------------------------------
# Fused computation-collective pipeline (docs/FUSED_COLLECTIVES.md)
# ---------------------------------------------------------------------------

def _fused_env(monkeypatch, chunk_bytes=256):
    """Arm the fused pipeline with a tiny chunk size so every test
    buffer actually splits into several chunks."""
    monkeypatch.setenv("HOROVOD_FUSED_COLLECTIVES", "1")
    monkeypatch.setenv("HOROVOD_FUSED_CHUNK_BYTES", str(chunk_bytes))


def test_plan_chunks_alignment_and_coverage():
    from horovod_tpu.ops.fused_collectives import plan_chunks
    for n, cb in ((100000, 65536), (128, 65536), (5000, 1024),
                  (1, 1024)):
        ch = plan_chunks(n, 4, chunk_bytes=cb)
        assert all(off % 128 == 0 for off, _ in ch)
        assert sum(w for _, w in ch) == n
        offs = [off for off, _ in ch]
        assert offs == sorted(offs)


def test_pipelined_grouped_allreduce_bitwise(mesh):
    """The chunked exact grouped allreduce must be BITWISE-equal to the
    unfused one — psum is elementwise, so chunk boundaries cannot move
    any element's reduction."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.ops import collectives as C
    from horovod_tpu.ops.fused_collectives import \
        pipelined_grouped_allreduce

    rng = np.random.RandomState(31)
    a = jnp.asarray(rng.randn(N, 300).astype(np.float32))
    b = jnp.asarray(rng.randn(N, 7, 5).astype(np.float32))
    c = jnp.asarray(rng.randint(0, 9, (N, 11)).astype(np.int32))

    def run(fn):
        def f(x, y, z):
            return tuple(fn([x[0], y[0], z[0]]))
        sm = jax.jit(shard_map(
            f, mesh=mesh, in_specs=(P(hvd.GLOBAL_AXIS),) * 3,
            out_specs=(P(),) * 3, check_vma=False))
        return [np.asarray(o) for o in sm(a, b, c)]

    ref = run(lambda ts: C.grouped_allreduce(
        ts, op=C.Average, axis_name=hvd.GLOBAL_AXIS))
    got = run(lambda ts: pipelined_grouped_allreduce(
        ts, op=C.Average, axis_name=hvd.GLOBAL_AXIS, chunk_bytes=256))
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)


def test_pipelined_allgather_shard_bitwise_on_wires(mesh):
    """Block-aligned chunking keeps every codec's scale-block boundaries
    where the whole-buffer encode puts them: the chunked gather is
    bitwise-equal for exact AND cooperative wires, including a
    non-block-multiple tail."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from jax import lax
    from horovod_tpu.ops.fused_collectives import pipelined_allgather_shard
    from horovod_tpu.ops.quantized import quantized_allgather_shard

    rng = np.random.RandomState(32)
    shard = jnp.asarray(rng.randn(N, 300).astype(np.float32))

    def run(fn):
        sm = jax.jit(shard_map(
            lambda x: fn(x[0]), mesh=mesh,
            in_specs=(P(hvd.GLOBAL_AXIS),), out_specs=P(),
            check_vma=False))
        return np.asarray(sm(shard))

    ax = hvd.GLOBAL_AXIS
    exact_ref = run(lambda s: lax.all_gather(s, ax, tiled=True))
    exact_got = run(lambda s: pipelined_allgather_shard(
        s, ax, chunk_bytes=512))
    np.testing.assert_array_equal(exact_ref, exact_got)
    for wire in ("int8", "int4"):
        ref = run(lambda s, w=wire: quantized_allgather_shard(
            s, ax, wire=w))
        got = run(lambda s, w=wire: pipelined_allgather_shard(
            s, ax, wire=w, chunk_bytes=512))
        np.testing.assert_array_equal(ref, got)


def test_pipelined_psum_scatter_bitwise(mesh):
    from jax import shard_map, lax
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.ops.fused_collectives import pipelined_psum_scatter

    rng = np.random.RandomState(33)
    flat = jnp.asarray(rng.randn(N, N * 137).astype(np.float32))

    def run(fn):
        sm = jax.jit(shard_map(
            lambda x: fn(x[0]), mesh=mesh,
            in_specs=(P(hvd.GLOBAL_AXIS),),
            out_specs=P(hvd.GLOBAL_AXIS), check_vma=False))
        return np.asarray(sm(flat))

    ax = hvd.GLOBAL_AXIS
    ref = run(lambda x: lax.psum_scatter(x, ax, tiled=True)[None])
    got = run(lambda x: pipelined_psum_scatter(
        x, ax, chunk_bytes=256)[None])
    np.testing.assert_array_equal(ref, got)


def test_pipelined_allreduce_shard_tolerance_and_ef(mesh):
    """The chunked quantized ring re-partitions the per-rank ring
    sub-chunks, so it agrees with the whole-buffer ring to wire
    tolerance (same contract as bucket-order permutation); the EF
    residual keeps the telescoping shape contract."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.ops.fused_collectives import pipelined_allreduce_shard

    rng = np.random.RandomState(34)
    flat = jnp.asarray(rng.randn(N, 2048).astype(np.float32))
    ef = jnp.zeros((N, 2048), jnp.float32)
    exact = np.mean(np.asarray(flat), axis=0)

    sm = jax.jit(shard_map(
        lambda x, e: pipelined_allreduce_shard(
            x[0], hvd.GLOBAL_AXIS, average=True, wire="int8",
            error_feedback=e[0], chunk_bytes=1024),
        mesh=hvd.global_mesh(),
        in_specs=(P(hvd.GLOBAL_AXIS),) * 2, out_specs=(P(), P()),
        check_vma=False))
    red, resid = sm(flat, ef)
    scale = np.abs(exact).max()
    assert np.abs(np.asarray(red) - exact).max() < N * scale / 50
    assert resid.shape == (2048,)
    # the residual is exactly input-minus-wire per chunk: nonzero
    assert float(np.abs(np.asarray(resid)).max()) > 0


def test_fused_matmul_reduce_scatter_matches_unfused(mesh):
    from jax import shard_map, lax
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.ops.fused_collectives import \
        fused_matmul_reduce_scatter

    rng = np.random.RandomState(35)
    a = jnp.asarray(rng.randn(N, 16, 24).astype(np.float32))
    b = jnp.asarray(rng.randn(N, 24, 33).astype(np.float32))

    def run(fn):
        sm = jax.jit(shard_map(
            lambda x, y: fn(x[0], y[0]), mesh=mesh,
            in_specs=(P(hvd.GLOBAL_AXIS),) * 2, out_specs=P(),
            check_vma=False))
        return np.asarray(sm(a, b))

    ax = hvd.GLOBAL_AXIS
    ref = run(lambda x, y: lax.psum_scatter(
        x @ y, ax, scatter_dimension=0, tiled=True))
    got = run(lambda x, y: fused_matmul_reduce_scatter(
        x, y, ax, chunk_bytes=256))
    assert got.shape == ref.shape == (16 // N, 33)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_fused_allgather_matmul_matches_unfused(mesh):
    from jax import shard_map, lax
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.ops.fused_collectives import fused_allgather_matmul

    rng = np.random.RandomState(36)
    x = jnp.asarray(rng.randn(N, 6, 20).astype(np.float32))
    w = jnp.asarray(rng.randn(N, 9, 20).astype(np.float32))

    def run(fn):
        sm = jax.jit(shard_map(
            lambda xx, ww: fn(xx[0], ww[0]), mesh=mesh,
            in_specs=(P(hvd.GLOBAL_AXIS),) * 2, out_specs=P(),
            check_vma=False))
        return np.asarray(sm(x, w))

    ax = hvd.GLOBAL_AXIS
    ref = run(lambda xx, ww: xx @ lax.all_gather(ww, ax, tiled=True).T)
    got = run(lambda xx, ww: fused_allgather_matmul(
        xx, ww, ax, chunk_bytes=256))
    assert got.shape == ref.shape == (6, N * 9)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_fused_routing_bitwise_exact_wire(mesh, monkeypatch):
    """HOROVOD_FUSED_COLLECTIVES=1 on the exact wire must not move a
    single bit of allreduce_gradients — across forward AND reverse
    bucket orders, and composed with the guard sentinel."""
    leaves = _order_test_leaves()
    base = {}
    for order in ("forward", "reverse"):
        base[order] = _bucketed_reduce(mesh, leaves, order)
    _fused_env(monkeypatch)
    for order in ("forward", "reverse"):
        got = _bucketed_reduce(mesh, leaves, order)
        for a, b in zip(base[order], got):
            np.testing.assert_array_equal(a, b)


def test_fused_routing_sentinel_composes(mesh, monkeypatch):
    """sentinel=True under the fused pipeline: same reduced values
    bitwise, and the per-bucket flag vector keeps its shape/zeros on
    finite inputs."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    leaves = _order_test_leaves()

    def run():
        def f(*xs):
            outs, flags = hvd.allreduce_gradients(
                [x[0] for x in xs], axis_name=hvd.GLOBAL_AXIS,
                fusion_threshold_bytes=512, sentinel=True)
            return tuple(outs) + (flags,)
        sm = jax.jit(shard_map(
            f, mesh=mesh, in_specs=(P(hvd.GLOBAL_AXIS),) * len(leaves),
            out_specs=tuple(P() for _ in range(len(leaves) + 1)),
            check_vma=False))
        outs = sm(*leaves)
        return [np.asarray(o) for o in outs[:-1]], np.asarray(outs[-1])

    ref, rflags = run()
    _fused_env(monkeypatch)
    got, gflags = run()
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(rflags, gflags)
    assert float(gflags.max()) == 0.0


def test_fused_routing_quantized_wire_tolerance(mesh, monkeypatch):
    """Cooperative wires under the fused pipeline: chunking moves the
    ring's internal sub-chunk boundaries, so parity is to wire
    tolerance (the documented contract), not bitwise."""
    leaves = _order_test_leaves()
    exact = [np.mean(np.asarray(l), axis=0) for l in leaves]
    _fused_env(monkeypatch)
    got = _bucketed_reduce(mesh, leaves, "reverse",
                           compression=hvd.Compression.int8)
    scale = max(np.abs(e).max() for e in exact)
    for g, e in zip(got, exact):
        assert np.abs(g - e).max() < N * scale / 50
