"""Statistics of the bench's gate metric (sim_scaling_efficiency):
median-of-pairs, raw (unclamped) per-pair ratios, central-3 spread on
widened runs, and adaptive widening — the machinery the r03 verdict
asked to be gate-quality."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402


def _feed(monkeypatch, times):
    """times: list of (t1, t8) per pair (+ final t8_nodist appended)."""
    seq = []
    for t1, t8 in times:
        seq += [t1, t8]
    seq.append(times[-1][1])     # the compute-only probe
    it = iter(seq)
    monkeypatch.setattr(bench, "_run_sim",
                        lambda n, dist, timeout: next(it))


class TestSimScalingStats:
    def test_median_of_three_pairs(self, monkeypatch):
        _feed(monkeypatch, [(1.0, 8.9), (1.0, 8.7), (1.0, 8.8)])
        median, spread, effs = bench.sim_scaling_efficiency(runs=3)
        assert effs == pytest.approx([8 / 8.9, 8 / 8.7, 8 / 8.8])
        assert median == pytest.approx(8 / 8.8)
        assert spread == pytest.approx(8 / 8.7 - 8 / 8.9)

    def test_ratios_stay_raw_above_one(self, monkeypatch):
        # Contention-inflated t1 pushes a pair above 1.0: the raw value
        # must be kept (clamping per pair would bias the median up).
        # Widening disabled so exactly 3 pairs are consumed.
        monkeypatch.setenv("HOROVOD_BENCH_SIM_MAX_RUNS", "3")
        _feed(monkeypatch, [(1.5, 8.0), (1.0, 8.9), (1.0, 9.0)])
        median, spread, effs = bench.sim_scaling_efficiency(runs=3)
        assert effs[0] == pytest.approx(1.5)
        assert median == pytest.approx(8 / 8.9)

    def test_adaptive_widening_and_central3_spread(self, monkeypatch):
        # Blown spread after 3 pairs -> widen to 5; spread over the
        # central 3 order statistics.
        monkeypatch.setenv("HOROVOD_BENCH_SIM_MAX_RUNS", "5")
        _feed(monkeypatch, [(1.0, 8.0), (0.5, 8.0), (1.0, 8.2),
                            (1.0, 8.4), (1.0, 8.6)])
        median, spread, effs = bench.sim_scaling_efficiency(runs=3)
        assert len(effs) == 5
        s = sorted(effs)
        assert median == pytest.approx(s[2])
        assert spread == pytest.approx(s[3] - s[1])

    def test_failed_pair_retried(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_BENCH_SIM_MAX_RUNS", "3")
        seq = [1.0, None, 1.0, 8.9, 1.0, 8.8, 1.0, 8.7, 8.5]
        it = iter(seq)
        monkeypatch.setattr(bench, "_run_sim",
                            lambda n, dist, timeout: next(it))
        median, spread, effs = bench.sim_scaling_efficiency(runs=3)
        assert len(effs) == 3   # the failed attempt was retried
