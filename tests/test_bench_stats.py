"""Statistics of the bench's gate metric (sim_scaling_efficiency):
paired runs, eff>1.0 rejection, trimmed median, central-3 spread,
bootstrap CI, and adaptive widening — the estimator the r04 verdict
asked to be gate-quality (task 4)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402


def _feed(monkeypatch, times):
    """times: list of (t1, t8) per pair; the compute-only, legacy,
    sharded, quantized, guard and fused pipeline probes of the extras
    block are fed the last pair's t8."""
    seq = []
    for t1, t8 in times:
        seq += [t1, t8]
    seq.append(times[-1][1])     # the compute-only probe
    seq.append(times[-1][1])     # the legacy-pipeline probe
    seq.append(times[-1][1])     # the sharded-pipeline probe
    seq.append(times[-1][1])     # the quantized-wire probe
    seq.append(times[-1][1])     # the guard-pipeline probe
    seq.append(times[-1][1])     # the fused-pipeline probe
    it = iter(seq)
    monkeypatch.setattr(
        bench, "_run_sim",
        lambda n, dist, timeout, legacy=False, sharded=False,
        quant=False, guard=False, fused=False: next(it))


class TestSimScalingStats:
    def test_median_of_three_pairs(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_BENCH_SIM_MAX_RUNS", "3")
        _feed(monkeypatch, [(1.0, 8.9), (1.0, 8.7), (1.0, 8.8)])
        median, spread, effs, ci, rejected, extras = \
            bench.sim_scaling_efficiency(runs=3)
        assert effs == pytest.approx([8 / 8.9, 8 / 8.7, 8 / 8.8])
        assert median == pytest.approx(8 / 8.8)
        assert spread == pytest.approx(8 / 8.7 - 8 / 8.9)
        assert rejected == 0
        assert min(effs) <= ci[0] <= ci[1] <= max(effs)
        # Extras: both pipelines' collective-share decomposition rides
        # along.  The probes are fed the median t8, so share == 0 here.
        assert extras["t8_ms"] == pytest.approx(8800.0)
        assert extras["collective_share"] == pytest.approx(0.0)
        assert extras["collective_share_legacy"] == pytest.approx(0.0)
        assert extras["collective_share_sharded"] == pytest.approx(0.0)
        # Guard probe fed the median t8 -> zero sentinel overhead.
        assert extras["t8_guard_ms"] == pytest.approx(8800.0)
        assert extras["guard_overhead"] == pytest.approx(0.0)
        # Fused probe fed the compute-only t8 -> zero collective share.
        assert extras["t8_fused_ms"] == pytest.approx(8800.0)
        assert extras["collective_share_fused"] == pytest.approx(0.0)
        # Stubbed probe leaves no child record -> no occupancy stats.
        assert "fused_occupancy_mean" not in extras
        # Stubbed probes leave no child record, so the byte comparison
        # is (correctly) absent rather than fabricated.
        assert "opt_state_bytes_sharded" not in extras

    def test_pairs_above_one_rejected(self, monkeypatch):
        # Contention-inflated t1 pushes a pair above 1.0: superlinear
        # scaling is impossible on the shared-core mesh, so the pair is
        # an invalid measurement and must be DISCARDED (r04 verdict) —
        # neither kept (blows the spread) nor clamped (biases up).
        monkeypatch.setenv("HOROVOD_BENCH_SIM_MAX_RUNS", "3")
        _feed(monkeypatch, [(1.5, 8.0), (1.0, 8.9), (1.0, 9.0),
                            (1.0, 8.8)])
        median, spread, effs, ci, rejected, extras = \
            bench.sim_scaling_efficiency(runs=3)
        assert rejected == 1
        assert all(e <= 1.0 for e in effs)
        assert len(effs) == 3
        assert median == pytest.approx(8 / 8.9)

    def test_adaptive_widening_and_trimmed_median(self, monkeypatch):
        # Blown spread after 3 pairs -> widen to 5; the trimmed median
        # (drop min/max) equals the middle order statistic; spread over
        # the central 3.
        monkeypatch.setenv("HOROVOD_BENCH_SIM_MAX_RUNS", "5")
        _feed(monkeypatch, [(1.0, 8.0), (0.5, 8.0), (1.0, 8.2),
                            (1.0, 8.4), (1.0, 8.6)])
        median, spread, effs, ci, rejected, extras = \
            bench.sim_scaling_efficiency(runs=3)
        assert len(effs) == 5
        s = sorted(effs)
        assert median == pytest.approx(s[2])
        assert spread == pytest.approx(s[3] - s[1])

    def test_failed_pair_retried(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_BENCH_SIM_MAX_RUNS", "3")
        seq = [1.0, None, 1.0, 8.9, 1.0, 8.8, 1.0, 8.7,
               8.5, 8.6, 8.6, 8.6, 8.6, 8.6]
        it = iter(seq)
        monkeypatch.setattr(
            bench, "_run_sim",
            lambda n, dist, timeout, legacy=False, sharded=False,
            quant=False, guard=False, fused=False: next(it))
        median, spread, effs, ci, rejected, extras = \
            bench.sim_scaling_efficiency(runs=3)
        assert len(effs) == 3   # the failed attempt was retried
        assert rejected == 0

    def test_ci_deterministic_and_ordered(self, monkeypatch):
        # The bootstrap seed is fixed: the CI is a function of the data,
        # not of the run.
        times = [(1.0, 8.9), (1.0, 8.7), (1.0, 8.8), (1.0, 8.6),
                 (1.0, 8.75), (1.0, 8.85), (1.0, 8.65)]
        monkeypatch.setenv("HOROVOD_BENCH_SIM_MAX_RUNS", "7")
        _feed(monkeypatch, times)
        r1 = bench.sim_scaling_efficiency(runs=7)
        _feed(monkeypatch, times)
        r2 = bench.sim_scaling_efficiency(runs=7)
        assert r1[3] == r2[3]
        assert r1[3][0] <= r1[0] <= r1[3][1]

    def test_too_few_valid_pairs_returns_none(self, monkeypatch):
        # Every pair invalid -> no estimate rather than a fabricated one.
        monkeypatch.setenv("HOROVOD_BENCH_SIM_MAX_RUNS", "3")
        seq = [1.5, 8.0] * 10 + [8.0]
        it = iter(seq)
        monkeypatch.setattr(
            bench, "_run_sim",
            lambda n, dist, timeout, legacy=False, sharded=False,
            quant=False, guard=False, fused=False: next(it))
        assert bench.sim_scaling_efficiency(runs=3) is None
