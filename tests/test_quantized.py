"""Quantized (int8-wire) allreduce tests — ops/quantized.py, the
EQuARX-style ring collective, plus its Compression.int8 routing in
allreduce_gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import horovod_tpu as hvd
from horovod_tpu.ops.quantized import (
    _dequant, _quant, quantized_allreduce,
)


@pytest.fixture()
def mesh8():
    devs = np.array(jax.devices()[:8])
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return Mesh(devs, ("r",))


class TestQuantPrimitives:
    def test_roundtrip_error_bounded_by_half_step(self):
        v = jnp.asarray(np.random.default_rng(0).normal(
            size=(1024,)).astype(np.float32)) * 10
        q, sc = _quant(v)
        assert q.dtype == jnp.int8
        back = _dequant(q, sc)
        # error <= scale/2 per element, blockwise
        step = np.repeat(np.asarray(sc), 128)
        assert np.all(np.abs(np.asarray(back - v)) <= step / 2 + 1e-6)

    def test_zero_block_is_exact(self):
        v = jnp.zeros((256,), jnp.float32)
        q, sc = _quant(v)
        np.testing.assert_array_equal(np.asarray(_dequant(q, sc)), 0.0)


class TestQuantizedAllreduce:
    def test_sum_close_to_exact(self, mesh8):
        rng = np.random.default_rng(1)
        contribs = rng.normal(size=(8, 1000)).astype(np.float32)
        out = np.asarray(quantized_allreduce(jnp.asarray(contribs), mesh8))
        exact = contribs.sum(0)
        # identical on every rank
        for r in range(1, 8):
            np.testing.assert_array_equal(out[r], out[0])
        # n-1 requantization hops: error ~ n * blockmax/254
        bound = 8 * np.abs(contribs).max() / 100
        assert np.abs(out[0] - exact).max() < bound

    def test_average_and_odd_sizes(self, mesh8):
        rng = np.random.default_rng(2)
        contribs = rng.normal(size=(8, 777)).astype(np.float32)
        out = np.asarray(quantized_allreduce(
            jnp.asarray(contribs), mesh8, average=True))
        exact = contribs.mean(0)
        assert np.abs(out[0] - exact).max() < 0.05

    def test_dtype_preserved(self, mesh8):
        contribs = jnp.ones((8, 256), jnp.bfloat16)
        out = quantized_allreduce(contribs, mesh8)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out[0], dtype=np.float32), 8.0, rtol=0.02)


class TestFp8Wire:
    def test_fp8_ring_large_magnitudes_no_nan(self, mesh8):
        # The scenario a wire-dtype psum would NaN on: 8 ranks of
        # magnitude ~100 sums to ~800 > e4m3's ±448 — the ring
        # accumulates in f32, so the result is finite and close.
        contribs = np.full((8, 512), 100.0, np.float32)
        out = np.asarray(quantized_allreduce(
            jnp.asarray(contribs), mesh8, wire="fp8_e4m3"))
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out[0], 800.0, rtol=0.05)

    # e4m3: 3 mantissa bits (rel step ~1/16); e5m2: 2 (~1/8) — both
    # coarser than int8's 1/127, so looser bounds than the int8 tests.
    @pytest.mark.parametrize("wire,bound",
                             [("fp8_e4m3", 0.15), ("fp8_e5m2", 0.3)])
    def test_fp8_ring_close_to_exact(self, mesh8, wire, bound):
        rng = np.random.default_rng(3)
        contribs = rng.normal(size=(8, 640)).astype(np.float32)
        out = np.asarray(quantized_allreduce(
            jnp.asarray(contribs), mesh8, wire=wire, average=True))
        exact = contribs.mean(0)
        assert np.abs(out[0] - exact).max() < bound

    def test_dp_gradient_path_fp8(self, mesh8):
        hvd.init()

        def f(grads):
            return hvd.allreduce_gradients(
                grads, compression=hvd.Compression.fp8_e4m3,
                axis_name=hvd.GLOBAL_AXIS)

        out = hvd.data_parallel(
            lambda s, o, b: (f({"g": jnp.full((256,), 100.0)}), o,
                             jnp.float32(0)))(
            {"x": jnp.zeros(())}, {}, hvd.shard_batch(
                (jnp.zeros((8, 1)),)))
        g = np.asarray(out[0]["g"])
        assert np.isfinite(g).all()
        np.testing.assert_allclose(g, 100.0, rtol=0.05)


class TestInt8GradientPath:
    def test_data_parallel_int8_matches_exact_closely(self, mesh8):
        import optax

        hvd.init()
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
        y = jax.random.normal(jax.random.PRNGKey(2), (64, 16))

        def fresh():
            k = jax.random.PRNGKey(0)
            w = {"w": jax.random.normal(k, (32, 16)),
                 "b": jnp.zeros((16,))}
            opt = optax.sgd(0.1)
            return w, opt, opt.init(w)

        def make_step(opt, comp):
            def step(params, opt_state, batch):
                xb, yb = batch

                def loss_fn(p):
                    return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

                loss, grads = jax.value_and_grad(loss_fn)(params)
                if comp is None:
                    grads = hvd.allreduce(grads)
                else:
                    grads = hvd.allreduce_gradients(
                        grads, compression=comp,
                        axis_name=hvd.GLOBAL_AXIS)
                updates, opt_state = opt.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state, loss
            return step

        sb = hvd.shard_batch((x, y))
        w1, opt1, s1 = fresh()
        pe, _, _ = hvd.data_parallel(make_step(opt1, None))(w1, s1, sb)
        w2, opt2, s2 = fresh()
        pq, _, _ = hvd.data_parallel(
            make_step(opt2, hvd.Compression.int8))(w2, s2, sb)
        assert float(jnp.abs(pq["w"] - pe["w"]).max()) < 5e-3

    def test_int8_outside_jit_raises(self):
        hvd.init()
        with pytest.raises(ValueError, match="in-jit path"):
            hvd.allreduce_gradients(
                {"g": jnp.ones((4,))}, compression=hvd.Compression.int8)
