"""Quantized (int8-wire) allreduce tests — ops/quantized.py, the
EQuARX-style ring collective, plus its Compression.int8 routing in
allreduce_gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import horovod_tpu as hvd
from horovod_tpu.ops.quantized import (
    _dequant, _quant, quantized_allreduce,
)


@pytest.fixture()
def mesh8():
    devs = np.array(jax.devices()[:8])
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return Mesh(devs, ("r",))


class TestQuantPrimitives:
    def test_roundtrip_error_bounded_by_half_step(self):
        v = jnp.asarray(np.random.default_rng(0).normal(
            size=(1024,)).astype(np.float32)) * 10
        q, sc = _quant(v)
        assert q.dtype == jnp.int8
        back = _dequant(q, sc)
        # error <= scale/2 per element, blockwise
        step = np.repeat(np.asarray(sc), 128)
        assert np.all(np.abs(np.asarray(back - v)) <= step / 2 + 1e-6)

    def test_zero_block_is_exact(self):
        v = jnp.zeros((256,), jnp.float32)
        q, sc = _quant(v)
        np.testing.assert_array_equal(np.asarray(_dequant(q, sc)), 0.0)


class TestQuantizedAllreduce:
    def test_sum_close_to_exact(self, mesh8):
        rng = np.random.default_rng(1)
        contribs = rng.normal(size=(8, 1000)).astype(np.float32)
        out = np.asarray(quantized_allreduce(jnp.asarray(contribs), mesh8))
        exact = contribs.sum(0)
        # identical on every rank
        for r in range(1, 8):
            np.testing.assert_array_equal(out[r], out[0])
        # n-1 requantization hops: error ~ n * blockmax/254
        bound = 8 * np.abs(contribs).max() / 100
        assert np.abs(out[0] - exact).max() < bound

    def test_average_and_odd_sizes(self, mesh8):
        rng = np.random.default_rng(2)
        contribs = rng.normal(size=(8, 777)).astype(np.float32)
        out = np.asarray(quantized_allreduce(
            jnp.asarray(contribs), mesh8, average=True))
        exact = contribs.mean(0)
        assert np.abs(out[0] - exact).max() < 0.05

    def test_dtype_preserved(self, mesh8):
        contribs = jnp.ones((8, 256), jnp.bfloat16)
        out = quantized_allreduce(contribs, mesh8)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out[0], dtype=np.float32), 8.0, rtol=0.02)


class TestFp8Wire:
    def test_fp8_ring_large_magnitudes_no_nan(self, mesh8):
        # The scenario a wire-dtype psum would NaN on: 8 ranks of
        # magnitude ~100 sums to ~800 > e4m3's ±448 — the ring
        # accumulates in f32, so the result is finite and close.
        contribs = np.full((8, 512), 100.0, np.float32)
        out = np.asarray(quantized_allreduce(
            jnp.asarray(contribs), mesh8, wire="fp8_e4m3"))
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out[0], 800.0, rtol=0.05)

    # e4m3: 3 mantissa bits (rel step ~1/16); e5m2: 2 (~1/8) — both
    # coarser than int8's 1/127, so looser bounds than the int8 tests.
    @pytest.mark.parametrize("wire,bound",
                             [("fp8_e4m3", 0.15), ("fp8_e5m2", 0.3)])
    def test_fp8_ring_close_to_exact(self, mesh8, wire, bound):
        rng = np.random.default_rng(3)
        contribs = rng.normal(size=(8, 640)).astype(np.float32)
        out = np.asarray(quantized_allreduce(
            jnp.asarray(contribs), mesh8, wire=wire, average=True))
        exact = contribs.mean(0)
        assert np.abs(out[0] - exact).max() < bound

    def test_dp_gradient_path_fp8(self, mesh8):
        hvd.init()

        def f(grads):
            return hvd.allreduce_gradients(
                grads, compression=hvd.Compression.fp8_e4m3,
                axis_name=hvd.GLOBAL_AXIS)

        out = hvd.data_parallel(
            lambda s, o, b: (f({"g": jnp.full((256,), 100.0)}), o,
                             jnp.float32(0)))(
            {"x": jnp.zeros(())}, {}, hvd.shard_batch(
                (jnp.zeros((8, 1)),)))
        g = np.asarray(out[0]["g"])
        assert np.isfinite(g).all()
        np.testing.assert_allclose(g, 100.0, rtol=0.05)


class TestInt8GradientPath:
    def test_data_parallel_int8_matches_exact_closely(self, mesh8):
        import optax

        hvd.init()
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
        y = jax.random.normal(jax.random.PRNGKey(2), (64, 16))

        def fresh():
            k = jax.random.PRNGKey(0)
            w = {"w": jax.random.normal(k, (32, 16)),
                 "b": jnp.zeros((16,))}
            opt = optax.sgd(0.1)
            return w, opt, opt.init(w)

        def make_step(opt, comp):
            def step(params, opt_state, batch):
                xb, yb = batch

                def loss_fn(p):
                    return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

                loss, grads = jax.value_and_grad(loss_fn)(params)
                if comp is None:
                    grads = hvd.allreduce(grads)
                else:
                    grads = hvd.allreduce_gradients(
                        grads, compression=comp,
                        axis_name=hvd.GLOBAL_AXIS)
                updates, opt_state = opt.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state, loss
            return step

        sb = hvd.shard_batch((x, y))
        w1, opt1, s1 = fresh()
        pe, _, _ = hvd.data_parallel(make_step(opt1, None))(w1, s1, sb)
        w2, opt2, s2 = fresh()
        pq, _, _ = hvd.data_parallel(
            make_step(opt2, hvd.Compression.int8))(w2, s2, sb)
        assert float(jnp.abs(pq["w"] - pe["w"]).max()) < 5e-3

    def test_int8_outside_jit_raises(self):
        hvd.init()
        with pytest.raises(ValueError, match="in-jit path"):
            hvd.allreduce_gradients(
                {"g": jnp.ones((4,))}, compression=hvd.Compression.int8)


class TestErrorFeedback:
    """EF compression (r5): residual bookkeeping and telescoping bias
    cancellation on the quantized wire."""

    def _run_ef(self, mesh8, grads_per_rank, steps, wire="int8"):
        """Iterate allreduce_gradients with EF on CONSTANT per-rank
        grads; returns list of per-step outputs (rank-0 view)."""
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        hvd.init()
        stacked = jnp.stack(grads_per_rank)        # [8, L]

        def one(x, e):
            out, e2 = hvd.allreduce_gradients(
                [x[0]], compression=hvd.Compression.int8,
                axis_name="r", error_feedback_state=e)
            return out[0][None], [a[None] for a in e2]

        sm = jax.jit(shard_map(
            one, mesh=mesh8,
            in_specs=(P("r"), [P("r")]),
            out_specs=(P("r"), [P("r")]),
            check_vma=False))
        e = [jnp.zeros_like(stacked)]
        outs = []
        for _ in range(steps):
            o, e = sm(stacked, e)
            outs.append(np.asarray(o[0]))
        return outs

    def test_conservation_identity_exact(self, mesh8):
        # The sender-side EF contract (quantized_allreduce_shard): every
        # bit the wire drops at step t sits in some rank's residual, so
        #   n * out_t == sum_r g_r + sum_r e_t - sum_r e_{t+1}
        # holds EXACTLY (f32 noise), not just statistically.
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        hvd.init()
        rng = np.random.default_rng(3)
        stacked = jnp.asarray(
            rng.normal(size=(8, 256)).astype(np.float32))

        def one(x, e):
            out, e2 = hvd.allreduce_gradients(
                [x[0]], compression=hvd.Compression.int8,
                axis_name="r", error_feedback_state=e)
            return out[0][None], [a[None] for a in e2]

        sm = jax.jit(shard_map(
            one, mesh=mesh8, in_specs=(P("r"), [P("r")]),
            out_specs=(P("r"), [P("r")]), check_vma=False))
        e = [jnp.zeros_like(stacked)]
        S = np.sum(np.asarray(stacked), axis=0)
        for _ in range(3):
            e_before = np.sum(np.asarray(e[0]), axis=0)
            out, e = sm(stacked, e)
            e_after = np.sum(np.asarray(e[0]), axis=0)
            lhs = 8.0 * np.asarray(out[0])        # Average -> sum
            np.testing.assert_allclose(
                lhs, S + e_before - e_after, atol=2e-3, rtol=1e-5)

    def test_compressor_bias_telescopes_away(self):
        # The EF recursion against the LOCAL compressor C (the operator
        # whose error is fed back): mean_t C(g + e_t) -> g with error
        # O(1/t) — the classic telescoping identity.
        from horovod_tpu.ops.quantized import local_roundtrip

        g = jnp.asarray(np.random.default_rng(5).normal(
            size=(512,)).astype(np.float32) * 3)
        e = jnp.zeros_like(g)
        outs = []
        for _ in range(12):
            c = local_roundtrip(g + e)
            e = (g + e) - c
            outs.append(np.asarray(c))
        single = np.abs(outs[0] - np.asarray(g)).mean()
        mean_err = np.abs(np.mean(outs, 0) - np.asarray(g)).mean()
        assert mean_err < single / 5, (mean_err, single)

    def test_bias_telescopes_through_the_ring(self, mesh8):
        # End-to-end O(1/t): sender-side EF captures EVERY wire
        # encode's error (first-hop, interior re-encodes, final
        # broadcast), so over 10 steps the time-averaged error drops to
        # ~1/10 of a single shot (measured r5: ratio 0.104).
        rng = np.random.default_rng(7)
        grads = [rng.normal(size=(512,)).astype(np.float32) * 3
                 for _ in range(8)]
        exact = np.mean(np.stack(grads), axis=0)
        outs = self._run_ef(mesh8, grads, steps=10)
        single_err = np.abs(outs[0] - exact).mean()
        mean_err = np.abs(np.mean(outs, axis=0) - exact).mean()
        assert mean_err < single_err * 0.2, (mean_err, single_err)

    def test_ef_requires_quantized_wire(self):
        hvd.init()
        with pytest.raises(ValueError, match="error_feedback"):
            hvd.allreduce_gradients(
                {"g": jnp.ones((4,))},
                compression=hvd.Compression.fp16,
                error_feedback_state=[jnp.zeros((4,))])

    def test_ef_leaf_count_mismatch_raises(self, mesh8):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        hvd.init()
        stacked = jnp.ones((8, 128), jnp.float32)

        def one(x, e):
            out, e2 = hvd.allreduce_gradients(
                [x[0], x[0]], compression=hvd.Compression.int8,
                axis_name="r", error_feedback_state=e)
            return out[0][None], [a[None] for a in e2]

        sm = shard_map(one, mesh=mesh8, in_specs=(P("r"), [P("r")]),
                       out_specs=(P("r"), [P("r")]), check_vma=False)
        with pytest.raises(ValueError, match="error_feedback_init"):
            jax.jit(sm)(stacked, [jnp.zeros((8, 128))])

    def test_error_feedback_init_float_leaves_only(self):
        grads = {"w": jnp.ones((3, 2)), "step": jnp.ones((), jnp.int32)}
        st = hvd.error_feedback_init(grads)
        assert len(st) == 1 and st[0].shape == (3, 2)
        assert st[0].dtype == jnp.float32

    def test_single_rank_applies_residual(self):
        # Shrunk-to-one-rank collective: the carried residual must be
        # APPLIED (out = x + e), not dropped (r5 review).
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        hvd.init()
        mesh1 = Mesh(np.array(jax.devices()[:1]), ("r",))
        x = jnp.ones((1, 128), jnp.float32)
        e = jnp.full((1, 128), 0.25, jnp.float32)

        def one(x, e):
            out, e2 = hvd.allreduce_gradients(
                [x[0]], compression=hvd.Compression.int8,
                axis_name="r", error_feedback_state=e)
            return out[0][None], [a[None] for a in e2]

        sm = jax.jit(shard_map(one, mesh=mesh1,
                               in_specs=(P("r"), [P("r")]),
                               out_specs=(P("r"), [P("r")]),
                               check_vma=False))
        out, e2 = sm(x, [e])
        np.testing.assert_allclose(np.asarray(out[0]), 1.25)
        np.testing.assert_allclose(np.asarray(e2[0]), 0.0)


class TestInt4Ring:
    def test_int4_ring_close_to_exact(self, mesh8):
        rng = np.random.default_rng(21)
        contribs = rng.normal(size=(8, 1024)).astype(np.float32)
        out = np.asarray(quantized_allreduce(
            jnp.asarray(contribs), mesh8, wire="int4"))
        exact = contribs.sum(0)
        for r in range(1, 8):
            np.testing.assert_array_equal(out[r], out[0])
        # n-1 requantization hops at ±7 levels: error ~ n * blockmax/14
        bound = 8 * np.abs(contribs).max() / 7
        err = np.abs(out[0] - exact).max()
        assert 0 < err < bound

    def test_int4_ef_telescopes(self, mesh8):
        rng = np.random.default_rng(22)
        grads = [rng.normal(size=(512,)).astype(np.float32) * 3
                 for _ in range(8)]
        exact = np.mean(np.stack(grads), axis=0)
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        hvd.init()
        stacked = jnp.stack([jnp.asarray(g) for g in grads])

        def one(x, e):
            out, e2 = hvd.allreduce_gradients(
                [x[0]], compression=hvd.Compression.int4,
                axis_name="r", error_feedback_state=e)
            return out[0][None], [a[None] for a in e2]

        sm = jax.jit(shard_map(
            one, mesh=mesh8, in_specs=(P("r"), [P("r")]),
            out_specs=(P("r"), [P("r")]), check_vma=False))
        e = [jnp.zeros_like(stacked)]
        outs = []
        for _ in range(10):
            o, e = sm(stacked, e)
            outs.append(np.asarray(o[0]))
        single_err = np.abs(outs[0] - exact).mean()
        mean_err = np.abs(np.mean(outs, axis=0) - exact).mean()
        assert mean_err < single_err * 0.2, (mean_err, single_err)


class TestMeshLevelErrorFeedback:
    """r6 satellite: the mesh-level quantized_allreduce accepts
    error_feedback like the shard-level primitive."""

    def test_conservation_identity(self, mesh8):
        rng = np.random.default_rng(23)
        contribs = jnp.asarray(
            rng.normal(size=(8, 256)).astype(np.float32))
        ef = jnp.zeros_like(contribs)
        S = np.sum(np.asarray(contribs), axis=0)
        for _ in range(3):
            e_before = np.sum(np.asarray(ef), axis=0)
            out, ef = quantized_allreduce(
                contribs, mesh8, error_feedback=ef)
            e_after = np.sum(np.asarray(ef), axis=0)
            np.testing.assert_allclose(
                np.asarray(out[0]), S + e_before - e_after,
                atol=2e-3, rtol=1e-5)

    def test_ef_improves_time_average(self, mesh8):
        rng = np.random.default_rng(24)
        contribs = jnp.asarray(
            rng.normal(size=(8, 512)).astype(np.float32) * 3)
        exact = np.mean(np.asarray(contribs), axis=0)
        no_ef = np.asarray(quantized_allreduce(
            contribs, mesh8, average=True, wire="int4"))[0]
        ef = jnp.zeros_like(contribs)
        outs = []
        for _ in range(10):
            out, ef = quantized_allreduce(
                contribs, mesh8, average=True, wire="int4",
                error_feedback=ef)
            outs.append(np.asarray(out)[0])
        single_err = np.abs(no_ef - exact).mean()
        mean_err = np.abs(np.mean(outs, axis=0) - exact).mean()
        assert mean_err < single_err * 0.2, (mean_err, single_err)


class TestQuantizedReduceScatterAllgather:
    """r6: the ring reduce-scatter / allgather shard primitives."""

    def _sm(self, mesh, fn, n_in=1):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P
        return jax.jit(shard_map(
            fn, mesh=mesh, in_specs=(P("r"),) * n_in,
            out_specs=P("r"), check_vma=False))

    @pytest.mark.parametrize("wire", ["int8", "int4", "fp8_e4m3"])
    def test_rs_matches_psum_scatter_ownership(self, mesh8, wire):
        from horovod_tpu.ops.quantized import (
            quantized_reducescatter_shard,
        )
        rng = np.random.default_rng(25)
        stacked = jnp.asarray(
            rng.normal(size=(8, 1024)).astype(np.float32))

        def rs(x):
            return quantized_reducescatter_shard(
                x[0], "r", wire=wire)[None]

        def exact_rs(x):
            import jax.lax as lax
            return lax.psum_scatter(x[0], "r", tiled=True)[None]

        out = np.asarray(self._sm(mesh8, rs)(stacked))
        ref = np.asarray(self._sm(mesh8, exact_rs)(stacked))
        assert out.shape == ref.shape == (8, 128)
        # same chunk ownership as psum_scatter, error within the
        # (n-1)-hop requantization bound
        bound = 8 * np.abs(np.asarray(stacked)).max() / \
            (100 if wire == "int8" else 6)
        assert np.abs(out - ref).max() < bound

    def test_rs_average_and_ef(self, mesh8):
        from horovod_tpu.ops.quantized import (
            quantized_reducescatter_shard,
        )
        rng = np.random.default_rng(26)
        stacked = jnp.asarray(
            rng.normal(size=(8, 1024)).astype(np.float32))

        def rs(x, e):
            own, e2 = quantized_reducescatter_shard(
                x[0], "r", average=True, wire="int8",
                error_feedback=e[0])
            return own[None], e2[None]

        from jax import shard_map
        from jax.sharding import PartitionSpec as P
        sm = jax.jit(shard_map(
            rs, mesh=mesh8, in_specs=(P("r"), P("r")),
            out_specs=(P("r"), P("r")), check_vma=False))
        own, resid = sm(stacked, jnp.zeros_like(stacked))
        exact = np.asarray(stacked).mean(0).reshape(8, 128)
        assert np.abs(np.asarray(own) - exact).max() < \
            np.abs(np.asarray(stacked)).max() / 10
        # every send's encode error lands in some residual
        assert np.abs(np.asarray(resid)).max() > 0

    def test_ag_matches_all_gather(self, mesh8):
        from horovod_tpu.ops.quantized import quantized_allgather_shard
        rng = np.random.default_rng(27)
        shards = jnp.asarray(
            rng.normal(size=(8, 128)).astype(np.float32))

        def ag(x):
            return quantized_allgather_shard(x[0], "r", wire="int8")[None]

        out = np.asarray(self._sm(mesh8, ag)(shards))
        exact = np.asarray(shards).reshape(-1)
        # every rank sees the same gathered vector, one encode of error
        for r in range(8):
            blocks = exact.reshape(-1, 128)
            step = np.repeat(np.abs(blocks).max(axis=1), 128) / 254
            assert np.all(np.abs(out[r] - exact) <= step + 1e-6)

    def test_ag_exact_wire_is_allgather(self, mesh8):
        from horovod_tpu.ops.quantized import quantized_allgather_shard
        shards = jnp.arange(8 * 16, dtype=jnp.float32).reshape(8, 16)

        def ag(x):
            return quantized_allgather_shard(x[0], "r", wire="none")[None]

        out = np.asarray(self._sm(mesh8, ag)(shards))
        np.testing.assert_array_equal(
            out[0], np.arange(128, dtype=np.float32))
