"""Autoscaler tests (horovod_tpu/serve/autoscale.py): decision-core
units on hand-built signal traces (hysteresis/dwell, cooldown, flap
suppression, the budget latch, min/max bounds, the degrade ladder),
tenant-priority shed order, the replayable decision log, the borrow
ledger's hand-back guarantee (including a reshard fault mid-stash),
the shaped loadgen traces, the sim A/B the bench records, and the
np=2-style slow e2e: a bursty trace makes grow fire, serve.replica_die
kills the joiner mid-grow, and the fleet converges digest-verified
with token-identical results."""

import dataclasses
import json
import os

import numpy as np
import pytest

import horovod_tpu.faults as _faults
from horovod_tpu.common.exceptions import InvalidRequestError
from horovod_tpu.parallel import reshard as _rs
from horovod_tpu.serve.autoscale import (
    AutoscaleConfig,
    AutoscaleController,
    BorrowLedger,
    SignalSnapshot,
    parse_tenant_classes,
    simulate_autoscale,
)
from horovod_tpu.serve.loadgen import SHAPES, make_shaped_trace
from horovod_tpu.serve.scheduler import ContinuousScheduler, Request


def _cfg(**kw):
    base = dict(min_replicas=1, max_replicas=4, cooldown_steps=6,
                dwell_steps=3, occ_high=0.85, occ_low=0.30,
                queue_wait_high_ms=1000.0,
                tenant_classes={"premium": 0, "standard": 1,
                                "batch": 2})
    base.update(kw)
    return AutoscaleConfig(**base)


def _snap(step, fleet=1, occ=0.5, depth=0, wait=0.0, **kw):
    return SignalSnapshot(step=step, fleet_size=fleet, occupancy=occ,
                          queue_depth=depth, queue_wait_ms=wait,
                          pool_free_frac=1.0 - occ, **kw)


def _pressure(step, fleet=1, **kw):
    return _snap(step, fleet=fleet, occ=0.95, depth=4, **kw)


def _relief(step, fleet=2, **kw):
    return _snap(step, fleet=fleet, occ=0.1, depth=0, **kw)


class TestDecisionCore:
    def test_dwell_gates_grow(self):
        c = AutoscaleController(_cfg(dwell_steps=3))
        assert c.observe(_pressure(0)).verdict == "hold"
        assert c.observe(_pressure(1)).verdict == "hold"
        assert c.observe(_pressure(2)).verdict == "grow"

    def test_broken_streak_resets_dwell(self):
        c = AutoscaleController(_cfg(dwell_steps=3))
        c.observe(_pressure(0))
        c.observe(_pressure(1))
        c.observe(_snap(2))                     # in band: streak resets
        assert c.observe(_pressure(3)).verdict == "hold"
        assert c.observe(_pressure(4)).verdict == "hold"
        assert c.observe(_pressure(5)).verdict == "grow"

    def test_cooldown_suppresses_next_event(self):
        c = AutoscaleController(_cfg(dwell_steps=1, cooldown_steps=5))
        d, _ = c.step(_pressure(0))
        assert d.verdict == "grow"
        for s in range(1, 6):                   # within cooldown
            d = c.observe(_pressure(s, fleet=2))
            assert d.verdict == "hold"
            assert "cooldown" in d.reason
        assert c.observe(_pressure(6, fleet=2)).verdict == "grow"

    def test_flap_suppression_doubles_reversal_cooldown(self):
        c = AutoscaleController(_cfg(dwell_steps=1, cooldown_steps=4,
                                     flap_mult=2))
        d, _ = c.step(_pressure(0))
        assert d.verdict == "grow"
        # A reversal (shrink) waits flap_mult * cooldown = 8, not 4.
        assert c.observe(_relief(6)).verdict == "hold"
        assert c.observe(_relief(8)).verdict == "hold"
        assert c.observe(_relief(9)).verdict == "shrink"

    def test_budget_latch_forbids_shrink(self):
        # Fleet at max so the breach latch can't route to grow: while
        # breaching or burning fast the controller must never shrink,
        # no matter how idle the fleet looks.
        c = AutoscaleController(_cfg(dwell_steps=1, cooldown_steps=0,
                                     max_replicas=2))
        assert c.observe(_relief(0, breaching=True)).verdict == "hold"
        assert c.observe(_relief(1, burn_fast=1.5)).verdict == "hold"
        assert c.observe(_relief(2)).verdict == "shrink"

    def test_min_max_bounds(self):
        c = AutoscaleController(_cfg(dwell_steps=1, cooldown_steps=0,
                                     max_replicas=2))
        assert c.observe(_relief(0, fleet=1)).verdict == "hold"
        d, _ = c.step(_pressure(1, fleet=2))     # at max, no backlog
        assert d.verdict == "shed"               # queue_depth=4 -> shed
        d = c.observe(_snap(2, fleet=2, occ=0.95, depth=0))
        # hot but nothing queued: not pressure, nothing to shed
        assert d.verdict == "hold"

    def test_degrade_ladder_borrow_then_shed(self):
        c = AutoscaleController(_cfg(dwell_steps=1, cooldown_steps=0,
                                     max_replicas=1))
        d = c.observe(_pressure(0, fleet=1, borrowable=1))
        assert d.verdict == "borrow"
        d = c.observe(_pressure(1, fleet=1, borrowable=0))
        assert d.verdict == "shed"

    def test_handback_before_shrink(self):
        c = AutoscaleController(_cfg(dwell_steps=1, cooldown_steps=0))
        d = c.observe(_relief(0, fleet=3, borrowed=1))
        assert d.verdict == "handback"
        d = c.observe(_relief(1, fleet=2, borrowed=0))
        assert d.verdict == "shrink"

    def test_replayed_decision_log_identical(self):
        trace = ([_pressure(s) for s in range(4)]
                 + [_snap(s) for s in range(4, 10)]
                 + [_relief(s, breaching=(s % 3 == 0))
                    for s in range(10, 20)])
        logs = []
        for _ in range(2):
            c = AutoscaleController(_cfg())
            for s in trace:
                c.step(s)
            logs.append(json.dumps(
                [dataclasses.asdict(d) for d in c.decisions],
                sort_keys=True))
        assert logs[0] == logs[1]

    def test_config_validation(self):
        with pytest.raises(InvalidRequestError):
            _cfg(min_replicas=3, max_replicas=2)
        with pytest.raises(InvalidRequestError):
            _cfg(occ_high=0.2, occ_low=0.5)
        with pytest.raises(InvalidRequestError):
            _cfg(dwell_steps=0)

    def test_config_env_knobs(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_AUTOSCALE_MIN_REPLICAS", "2")
        monkeypatch.setenv("HOROVOD_AUTOSCALE_MAX_REPLICAS", "5")
        monkeypatch.setenv("HOROVOD_AUTOSCALE_COOLDOWN", "11")
        monkeypatch.setenv("HOROVOD_AUTOSCALE_DWELL", "4")
        monkeypatch.setenv("HOROVOD_AUTOSCALE_OCC_HIGH", "0.7")
        monkeypatch.setenv("HOROVOD_AUTOSCALE_OCC_LOW", "0.2")
        monkeypatch.setenv("HOROVOD_AUTOSCALE_QUEUE_MS", "500")
        monkeypatch.setenv("HOROVOD_AUTOSCALE_TENANT_CLASSES",
                           "gold:0,bronze:5")
        cfg = AutoscaleConfig()
        assert (cfg.min_replicas, cfg.max_replicas) == (2, 5)
        assert (cfg.cooldown_steps, cfg.dwell_steps) == (11, 4)
        assert (cfg.occ_high, cfg.occ_low) == (0.7, 0.2)
        assert cfg.queue_wait_high_ms == 500.0
        assert cfg.tenant_classes == {"gold": 0, "bronze": 5}

    def test_parse_tenant_classes_rejects_garbage(self):
        with pytest.raises(InvalidRequestError):
            parse_tenant_classes("premium")
        with pytest.raises(InvalidRequestError):
            parse_tenant_classes("premium:x")
        with pytest.raises(InvalidRequestError):
            parse_tenant_classes(",")


class _Fleet:
    """Minimal actuator double recording calls."""

    def __init__(self, size=1, fail=False):
        self.size = size
        self.fail = fail
        self.sheds = []

    def fleet_size(self):
        return self.size

    def scale_to(self, n):
        if self.fail:
            raise RuntimeError("actuator down")
        self.size = n
        return n

    def shed(self, n):
        self.sheds.append(n)
        return min(n, 2)


class TestActuation:
    def test_scale_event_commits(self):
        fleet = _Fleet(1)
        c = AutoscaleController(_cfg(dwell_steps=1), actuator=fleet)
        d, ev = c.step(_pressure(0))
        assert (d.verdict, ev.state) == ("grow", "committed")
        assert fleet.size == 2 and ev.converged_size == 2

    def test_mid_event_fault_aborts_and_dumps(self, tmp_path):
        from horovod_tpu.serve.flightrec import FlightRecorder
        rec = FlightRecorder(64, out_dir=str(tmp_path))
        fleet = _Fleet(1, fail=True)
        c = AutoscaleController(_cfg(dwell_steps=1), actuator=fleet,
                                flightrec=rec)
        d, ev = c.step(_pressure(0))
        assert ev.state == "aborted"
        assert ev.converged_size == 1           # lease plane's answer
        dumps = [p for p in os.listdir(tmp_path)
                 if p.startswith("serve_flightrec")]
        assert len(dumps) == 1
        payload = json.load(open(tmp_path / dumps[0]))
        assert payload["reason"] == "scale_event_failed"
        kinds = [e["kind"] for e in payload["events"]]
        assert "autoscale" in kinds and "autoscale_abort" in kinds
        rec.close()

    def test_control_loop_outlives_aborted_events(self):
        fleet = _Fleet(1, fail=True)
        c = AutoscaleController(_cfg(dwell_steps=1, cooldown_steps=0),
                                actuator=fleet)
        for s in range(3):
            _, ev = c.step(_pressure(s))
            assert ev.state == "aborted"
        assert len(c.events) == 3               # never raised

    def test_shed_event_counts(self):
        fleet = _Fleet(2)
        c = AutoscaleController(
            _cfg(dwell_steps=1, max_replicas=2), actuator=fleet)
        d, ev = c.step(_pressure(0, fleet=2))
        assert (d.verdict, ev.state) == ("shed", "committed")
        assert fleet.sheds == [4] and c.shed_total == 2


class TestBorrowLedger:
    def test_borrow_handback_and_close_guarantee(self):
        lent, returned = [], []
        led = BorrowLedger(lambda n: lent.append(n) or n,
                           lambda n: returned.append(n), capacity=3)
        assert led.borrow(2) == 2
        assert led.borrow(5) == 1               # capped at capacity
        assert led.outstanding == 3 and led.borrowable() == 0
        assert led.handback(1) == 1
        assert led.close() == 2                 # everything back
        assert led.outstanding == 0 and sum(returned) == sum(lent)

    def test_borrow_fault_leaves_ledger_clean(self):
        def boom(n):
            raise RuntimeError("reshard peer died")
        led = BorrowLedger(boom, lambda n: None, capacity=2)
        c = AutoscaleController(
            _cfg(dwell_steps=1, max_replicas=1), ledger=led)
        d, ev = c.step(_pressure(0, fleet=1, borrowable=2))
        assert (d.verdict, ev.state) == ("borrow", "aborted")
        assert led.outstanding == 0

    def test_close_hands_back_on_drain(self):
        led = BorrowLedger(lambda n: n, lambda n: None, capacity=2)
        c = AutoscaleController(_cfg(), ledger=led)
        led.borrow(2)
        c.close()
        assert led.outstanding == 0


class TestBorrowStashRestore:
    """The real borrow edges: training rows roundtrip through the
    reshard plane (stash -> restore at any world size), and a peer
    dying mid-stash aborts with nothing recorded."""

    GROUPS = (10, 6)

    def _rows(self, n_old, rank):
        g0 = np.arange(10, dtype=np.float32) + 1
        g1 = np.arange(6, dtype=np.float32) * 0.5 - 1
        out = []
        for full in (g0, g1):
            s = -(-full.size // n_old)
            pad = np.zeros(s * n_old, full.dtype)
            pad[:full.size] = full
            out.append(pad.reshape(n_old, s))
        return out

    def test_roundtrip_any_world_size(self):
        from horovod_tpu.serve.handoff import (
            restore_train_state,
            stash_train_state,
        )
        t = _rs.LocalTransport()
        for rank in range(2):
            stash_train_state(self._rows(2, rank), self.GROUPS, 2,
                              rank, t)
        # Hand-back at a DIFFERENT world size (n_new=1): one rank
        # fetches everything.
        rows = restore_train_state(self.GROUPS, ("float32", "float32"),
                                   1, 0, t)
        np.testing.assert_array_equal(
            rows[0].reshape(-1)[:10],
            np.arange(10, dtype=np.float32) + 1)
        np.testing.assert_array_equal(
            rows[1].reshape(-1)[:6],
            np.arange(6, dtype=np.float32) * 0.5 - 1)

    def test_peer_die_mid_stash_aborts_borrow(self):
        from horovod_tpu.serve.handoff import stash_train_state
        t = _rs.LocalTransport()
        _faults.install("reshard.peer_die@1:err")
        try:
            def borrow_fn(n):
                stash_train_state(self._rows(2, 0), self.GROUPS, 2, 0,
                                  t)
                return n
            led = BorrowLedger(borrow_fn, lambda n: None, capacity=1)
            c = AutoscaleController(
                _cfg(dwell_steps=1, max_replicas=1), ledger=led)
            d, ev = c.step(_pressure(0, fleet=1, borrowable=1))
            assert ev.state == "aborted"
            assert led.outstanding == 0         # nothing recorded
        finally:
            _faults.clear()


class TestTenantShed:
    def _sched(self):
        sched = ContinuousScheduler(max_batch=2)
        for i, (cls, arr) in enumerate([("premium", 0), ("batch", 0),
                                        ("standard", 1), ("batch", 2),
                                        ("standard", 3)]):
            sched.submit(Request(req_id=i, prompt=np.ones(4, np.int32),
                                 max_new_tokens=2, arrival_step=arr,
                                 slo_class=cls), step=arr)
        return sched

    def test_shed_order_lowest_class_newest_first(self):
        sched = self._sched()
        shed = sched.shed(10, 4)
        # batch (newest first: req 3 then 1), then standard (4 then 2);
        # premium (req 0) survives.
        assert [r.req_id for r in shed] == [3, 1, 4, 2]
        assert [r.req_id for r in sched.queue] == [0]
        assert [e for e in sched.decision_log if e[1] == "shed"] == [
            (10, "shed", 3, -1), (10, "shed", 1, -1),
            (10, "shed", 4, -1), (10, "shed", 2, -1)]

    def test_shed_never_touches_active(self):
        sched = self._sched()
        sched.admit(5, lambda req: True)        # fills both rows
        n_active = len(sched.active)
        shed = sched.shed(5, 99)
        assert len(shed) == len(sched.queue) + len(shed) - \
            sched.queue_depth()                 # queued only
        assert len(sched.active) == n_active

    def test_unknown_class_sheds_first(self):
        sched = ContinuousScheduler(max_batch=1)
        for i, cls in enumerate(["standard", "mystery"]):
            sched.submit(Request(req_id=i, prompt=np.ones(2, np.int32),
                                 max_new_tokens=1, slo_class=cls),
                         step=0)
        shed = sched.shed(1, 1)
        assert [r.req_id for r in shed] == [1]


class TestSnapshotFromServer:
    def test_live_server_signals(self):
        import jax
        import jax.numpy as jnp

        from horovod_tpu.models import (
            TransformerConfig,
            transformer_init,
        )
        from horovod_tpu.serve import InferenceServer
        from horovod_tpu.serve.autoscale import snapshot_from_server
        cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                d_head=8, d_ff=64, n_layers=2,
                                compute_dtype=jnp.float32)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        srv = InferenceServer(params, cfg, max_seq_tokens=24,
                              max_batch=2, page_tokens=4)
        for _ in range(3):
            srv.submit(np.ones(4, np.int32), 2)
        s = snapshot_from_server(srv, step=5, fleet_size=2)
        assert (s.step, s.fleet_size) == (5, 2)
        assert s.queue_depth == 3                # nothing admitted yet
        assert s.pool_free_frac == 1.0
        assert s.occupancy == 0.0
        srv.step()
        s = snapshot_from_server(srv)
        assert s.occupancy > 0 and s.pool_free_frac < 1.0
        assert 0.0 <= s.pool_free_frac <= 1.0
        list(srv.run())
        s = snapshot_from_server(srv)
        assert s.queue_depth == 0 and s.pool_free_frac == 1.0


class TestShapedTraces:
    def test_shapes_deterministic_and_tagged(self):
        for shape in SHAPES:
            t1 = make_shaped_trace(shape, 3, 50, 64)
            t2 = make_shaped_trace(shape, 3, 50, 64)
            assert len(t1) == 50
            assert all(a[0] == b[0] and a[2] == b[2] and a[3] == b[3]
                       and np.array_equal(a[1], b[1])
                       for a, b in zip(t1, t2))
            arrivals = [it[0] for it in t1]
            assert arrivals == sorted(arrivals)
            assert all(it[3] in ("premium", "standard", "batch")
                       for it in t1)

    def test_burst_has_clumps(self):
        t = make_shaped_trace("burst", 0, 120, 64, base_every=4.0,
                              burst_every=32, burst_size=16)
        from collections import Counter
        peak = max(Counter(it[0] for it in t).values())
        assert peak >= 8                        # a real clump

    def test_multi_tenant_has_all_classes(self):
        t = make_shaped_trace("multi_tenant", 1, 60, 64)
        classes = {it[3] for it in t}
        assert classes == {"premium", "standard", "batch"}

    def test_unknown_shape_rejected(self):
        with pytest.raises(InvalidRequestError):
            make_shaped_trace("sawtooth", 0, 10, 64)


class TestSimBench:
    """The A/B the bench records: under the bursty trace the
    autoscaled fleet must beat a static fleet of the same mean size on
    SLO-violation-minutes (the acceptance anchor)."""

    def test_autoscaled_beats_static_on_burst(self):
        cfg = _cfg(max_replicas=8, cooldown_steps=4, dwell_steps=2,
                   grow_step=2)
        trace = make_shaped_trace("burst", 7, 500, 64, base_every=4.0,
                                  burst_every=128, burst_size=80)
        auto = simulate_autoscale(trace, cfg)
        static = simulate_autoscale(
            trace, cfg, static_size=max(1, round(auto["fleet_mean"])))
        assert auto["completed"] == 500
        assert auto["slo_violation_minutes"] < \
            static["slo_violation_minutes"]
        # Same mean size is the point of the comparison.
        assert abs(auto["fleet_mean"] - static["fleet_mean"]) < 0.5

    def test_sim_sheds_by_class_at_max(self):
        cfg = _cfg(max_replicas=1, cooldown_steps=2, dwell_steps=2)
        trace = make_shaped_trace("burst", 3, 200, 64, base_every=2.0,
                                  burst_every=32, burst_size=40)
        rec = simulate_autoscale(trace, cfg, max_batch=2,
                                 extra_steps=4096)
        assert rec["shed"] > 0
        # batch sheds first within every shed event, so it can never
        # shed less than premium (which only goes when nothing else
        # is queued).
        assert rec["shed_by_class"].get("batch", 0) > 0
        assert rec["shed_by_class"].get("batch", 0) >= \
            rec["shed_by_class"].get("premium", 0)


@pytest.mark.slow
class TestAutoscaleScaleChaosE2E:
    """Bursty trace drives the REAL control loop over a REAL
    two-replica fleet: grow fires, serve.replica_die kills the JOINING
    replica mid-grow, and the fleet must converge with digest
    agreement and token-identical results (no stop-the-world restore
    anywhere)."""

    CONFIG = {
        "cfg": dict(vocab_size=64, d_model=32, n_heads=4, d_head=8,
                    d_ff=64, n_layers=2, compute_dtype="float32"),
        "seed": 0,
        "serve": dict(max_seq_tokens=24, max_batch=2, page_tokens=4),
    }

    def _trace(self):
        return make_shaped_trace("burst", 2, 8, 64, prompt_lens=(4,),
                                 max_new_lo=2, max_new_hi=5,
                                 base_every=1.0, burst_every=4,
                                 burst_size=4)

    def _baseline(self):
        from horovod_tpu.serve.replica import ReplicaManager
        with ReplicaManager(1, self.CONFIG, lease_ttl=10.0,
                            respawn_backoff=0.2,
                            child_env={"JAX_PLATFORMS": "cpu"}) as mgr:
            for it in self._trace():
                mgr.submit(it[1].tolist(), it[2], slo_class=it[3])
            return mgr.wait_all(timeout=180)

    def test_grow_under_fire_converges_digest_verified(self):
        from horovod_tpu.serve.autoscale import (
            ReplicaFleetActuator,
            snapshot_from_manager,
        )
        from horovod_tpu.serve.replica import ReplicaManager
        baseline = self._baseline()
        with ReplicaManager(1, self.CONFIG, lease_ttl=10.0,
                            respawn_backoff=0.2,
                            child_env={"JAX_PLATFORMS": "cpu"}) as mgr:
            ctrl = AutoscaleController(
                _cfg(dwell_steps=2, cooldown_steps=2, max_replicas=2),
                actuator=ReplicaFleetActuator(mgr))
            for it in self._trace():
                mgr.submit(it[1].tolist(), it[2], slo_class=it[3])
            # The burst is outstanding: pressure builds, grow fires —
            # with the fault armed so the JOINER dies mid-scale-event.
            mgr.child_env.update({
                "HOROVOD_FAULT_SPEC": "serve.replica_die@3:exit:1",
                "HOROVOD_FAULT_HOSTS": "replica1",
            })
            grew = None
            for step in range(64):
                d, ev = ctrl.step(snapshot_from_manager(mgr, step,
                                                        max_batch=2))
                if ev is not None and d.verdict == "grow":
                    grew = ev
                    break
            assert grew is not None, \
                [d.verdict for d in ctrl.decisions]
            results = mgr.wait_all(timeout=180)
            mgr.child_env.pop("HOROVOD_FAULT_SPEC")
            mgr.child_env.pop("HOROVOD_FAULT_HOSTS")
            assert mgr._respawns >= 1           # the joiner died
            assert mgr.fleet_size() == 2        # ...and converged
            assert mgr.digest_agreement(timeout=60.0)  # no split brain
            assert results == baseline          # token-identical

    def test_run_scale_chaos_all_recover(self):
        from horovod_tpu.serve.autoscale import run_scale_chaos
        rec = run_scale_chaos(n_events=2, seed=0)
        assert rec["all_recovered"], rec
        assert any(e["faulted"] for e in rec["events"])
        assert rec["respawns"] >= 1
