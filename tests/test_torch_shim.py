"""Torch frontend shim tests (reference: test/parallel/test_torch.py's
API surface, adapted to the one-process sim).

On the 8-rank CPU mesh a plain tensor means "every rank contributes this
value", so Average round-trips values exactly — the assertions mirror the
reference's self-consistency checks plus optimizer/broadcast mechanics.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu.torch as hvd_torch  # noqa: E402


class TestTorchOps:
    def test_allreduce_roundtrip(self):
        t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
        out = hvd_torch.allreduce(t)
        assert isinstance(out, torch.Tensor)
        assert out.dtype == t.dtype
        torch.testing.assert_close(out, t)

    def test_allreduce_sum_scales_by_size(self):
        t = torch.ones(5)
        out = hvd_torch.allreduce(t, op=hvd_torch.Sum)
        torch.testing.assert_close(out, t * hvd_torch.size())

    def test_allreduce_inplace(self):
        t = torch.ones(3)
        ret = hvd_torch.allreduce_(t, op=hvd_torch.Sum)
        assert ret is t
        torch.testing.assert_close(t, torch.full((3,),
                                                 float(hvd_torch.size())))

    def test_allgather_concats(self):
        t = torch.ones(2, 3)
        out = hvd_torch.allgather(t)
        assert out.shape == (2 * hvd_torch.size(), 3)

    def test_broadcast(self):
        t = torch.randn(4)
        out = hvd_torch.broadcast(t, root_rank=0)
        torch.testing.assert_close(out, t)

    def test_async_handle(self):
        t = torch.ones(3)
        h = hvd_torch.allreduce_async(t, op=hvd_torch.Sum)
        assert hvd_torch.poll(h)
        out = hvd_torch.synchronize(h)
        np.testing.assert_allclose(np.asarray(out),
                                   np.full(3, hvd_torch.size()))

    def test_grouped_allreduce(self):
        ts = [torch.ones(2), torch.full((3,), 2.0)]
        outs = hvd_torch.grouped_allreduce(ts)
        torch.testing.assert_close(outs[0], ts[0])
        torch.testing.assert_close(outs[1], ts[1])


class TestTorchBroadcastState:
    def test_broadcast_parameters_state_dict(self):
        model = torch.nn.Linear(4, 2)
        hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)

    def test_broadcast_optimizer_state(self):
        model = torch.nn.Linear(4, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        loss = model(torch.randn(8, 4)).sum()
        loss.backward()
        opt.step()
        hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
        assert opt.param_groups[0]["lr"] == pytest.approx(0.1)

    def test_broadcast_object(self):
        obj = {"epoch": 3, "arr": [1, 2, 3]}
        assert hvd_torch.broadcast_object(obj, root_rank=0) == obj


class TestTorchDistributedOptimizer:
    def _train_once(self, bpps=1):
        torch.manual_seed(0)
        model = torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.05)
        opt = hvd_torch.DistributedOptimizer(
            opt, named_parameters=model.named_parameters(),
            backward_passes_per_step=bpps)
        x = torch.randn(16, 4)
        y = x.sum(dim=1, keepdim=True)
        losses = []
        for i in range(10 * bpps):
            if i % bpps == 0:
                opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
            opt.step()
            losses.append(float(loss.detach()))
        return losses

    def test_training_reduces_loss(self):
        losses = self._train_once()
        assert losses[-1] < losses[0] * 0.7, losses

    def test_backward_passes_per_step(self):
        losses = self._train_once(bpps=2)
        assert losses[-1] < losses[0] * 0.9, losses

    def test_duplicate_names_rejected(self):
        model = torch.nn.Linear(2, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        dup = [("same", p) for p in model.parameters()]
        with pytest.raises(ValueError):
            hvd_torch.DistributedOptimizer(opt, named_parameters=dup)

    def test_passthrough_attrs(self):
        model = torch.nn.Linear(2, 2)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1))
        assert opt.param_groups[0]["lr"] == pytest.approx(0.1)


class TestCallbacks:
    def test_metric_average(self):
        from horovod_tpu import callbacks
        out = callbacks.MetricAverageCallback().on_epoch_end(
            {"acc": np.float32(0.5)})
        assert float(out["acc"]) == pytest.approx(0.5)

    def test_broadcast_globals_once(self):
        from horovod_tpu import callbacks
        import jax.numpy as jnp
        cb = callbacks.BroadcastGlobalVariablesCallback(0)
        state = {"w": jnp.ones((3,))}
        out1 = cb.on_train_begin(state)
        out2 = cb.on_train_begin(out1)
        assert out2 is out1  # second call is a no-op
        np.testing.assert_allclose(np.asarray(out1["w"]), 1.0)

    def test_warmup_lr(self):
        from horovod_tpu import callbacks
        cb = callbacks.LearningRateWarmupCallback(5, 0.8)
        assert cb.lr(0, 10, 0) == pytest.approx(0.8 / cb.size)
        assert cb.lr(5) == pytest.approx(0.8)
        mid = cb.lr(2, 10, 5)
        assert 0.8 / cb.size < mid < 0.8

    def test_schedule_lr(self):
        from horovod_tpu import callbacks
        cb = callbacks.LearningRateScheduleCallback(
            [dict(start_epoch=0, end_epoch=2, multiplier=1.0),
             dict(start_epoch=2, end_epoch=4, multiplier=0.1),
             dict(start_epoch=4, multiplier=lambda e: 0.01)],
            initial_lr=1.0)
        assert cb.lr(1) == 1.0
        assert cb.lr(3) == pytest.approx(0.1)
        assert cb.lr(10) == pytest.approx(0.01)
