"""Torch frontend shim tests (reference: test/parallel/test_torch.py's
API surface, adapted to the one-process sim).

On the 8-rank CPU mesh a plain tensor means "every rank contributes this
value", so Average round-trips values exactly — the assertions mirror the
reference's self-consistency checks plus optimizer/broadcast mechanics.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu.torch as hvd_torch  # noqa: E402


class TestTorchOps:
    def test_allreduce_roundtrip(self):
        t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
        out = hvd_torch.allreduce(t)
        assert isinstance(out, torch.Tensor)
        assert out.dtype == t.dtype
        torch.testing.assert_close(out, t)

    def test_allreduce_sum_scales_by_size(self):
        t = torch.ones(5)
        out = hvd_torch.allreduce(t, op=hvd_torch.Sum)
        torch.testing.assert_close(out, t * hvd_torch.size())

    def test_allreduce_inplace(self):
        t = torch.ones(3)
        ret = hvd_torch.allreduce_(t, op=hvd_torch.Sum)
        assert ret is t
        torch.testing.assert_close(t, torch.full((3,),
                                                 float(hvd_torch.size())))

    def test_allgather_concats(self):
        t = torch.ones(2, 3)
        out = hvd_torch.allgather(t)
        assert out.shape == (2 * hvd_torch.size(), 3)

    def test_broadcast(self):
        t = torch.randn(4)
        out = hvd_torch.broadcast(t, root_rank=0)
        torch.testing.assert_close(out, t)

    def test_reducescatter_slices(self):
        n = 2 * hvd_torch.size()
        t = torch.arange(2 * n, dtype=torch.float32).reshape(n, 2)
        out = hvd_torch.reducescatter(t)
        # Average over identical per-rank inputs == this rank's slice.
        assert out.shape == (n // hvd_torch.size(), 2)
        r = hvd_torch.rank()
        torch.testing.assert_close(out, t[2 * r:2 * r + 2])

    def test_reducescatter_async_roundtrip(self):
        n = 2 * hvd_torch.size()
        h = hvd_torch.reducescatter_async(torch.randn(n, 2))
        out = hvd_torch.synchronize(h)
        assert out.shape == (2, 2)

    def test_grouped_allgather(self):
        ts = [torch.ones(2, 3), torch.zeros(1, 3)]
        outs = hvd_torch.grouped_allgather(ts)
        assert [o.shape[0] for o in outs] == [
            2 * hvd_torch.size(), 1 * hvd_torch.size()]

    def test_grouped_reducescatter(self):
        n = hvd_torch.size()
        ts = [torch.ones(2 * n, 2), torch.ones(n)]
        outs = hvd_torch.grouped_reducescatter(ts)
        assert outs[0].shape == (2, 2)
        assert outs[1].shape == (1,)

    def test_async_handle(self):
        import time

        t = torch.ones(3)
        h = hvd_torch.allreduce_async(t, op=hvd_torch.Sum)
        # poll() must eventually report completion without synchronize().
        deadline = time.time() + 30
        while not hvd_torch.poll(h):
            assert time.time() < deadline, "collective never completed"
            time.sleep(0.01)
        out = hvd_torch.synchronize(h)
        np.testing.assert_allclose(np.asarray(out),
                                   np.full(3, hvd_torch.size()))

    def test_grouped_allreduce(self):
        ts = [torch.ones(2), torch.full((3,), 2.0)]
        outs = hvd_torch.grouped_allreduce(ts)
        torch.testing.assert_close(outs[0], ts[0])
        torch.testing.assert_close(outs[1], ts[1])


class TestCollectiveGradients:
    """Reference: torch/mpi_ops.py autograd Functions — collectives are
    differentiable; grad-of-allreduce is allreduce, grad-of-allgather is
    the summed gradient's own slice, grad-of-broadcast sums to root."""

    def test_allreduce_gradient(self):
        x = torch.ones(4, requires_grad=True)
        y = hvd_torch.allreduce(x * 2.0)
        y.sum().backward()
        torch.testing.assert_close(x.grad, torch.full((4,), 2.0))

    def test_allgather_gradient_sums_and_slices(self):
        x = torch.ones(2, 3, requires_grad=True)
        y = hvd_torch.allgather(x)
        assert y.shape[0] == 2 * hvd_torch.size()
        y.sum().backward()
        torch.testing.assert_close(
            x.grad, torch.full((2, 3), float(hvd_torch.size())))

    def test_broadcast_gradient_on_root(self):
        x = torch.ones(3, requires_grad=True)
        y = hvd_torch.broadcast(x, root_rank=0)
        y.sum().backward()
        # This process IS rank 0 in the sim: gradient sums across ranks.
        torch.testing.assert_close(
            x.grad, torch.full((3,), float(hvd_torch.size())))

    def test_no_grad_path_unchanged(self):
        y = hvd_torch.allreduce(torch.ones(3))
        assert not y.requires_grad


class TestSparseAllreduce:
    """Reference: torch/mpi_ops.py sparse_allreduce_async — gathered
    (indices, values) coalesced into the reduced sparse tensor.  Every
    sim rank contributes the same entries, so duplicates sum to
    size*values and Average restores the original."""

    def _sparse(self):
        i = torch.tensor([[0, 1, 3], [2, 0, 1]])
        v = torch.tensor([1.0, 2.0, 3.0])
        return torch.sparse_coo_tensor(i, v, size=(4, 4))

    def test_average_roundtrip(self):
        h = hvd_torch.sparse_allreduce_async(self._sparse(), name="s1")
        out = hvd_torch.synchronize(h)
        assert out.is_sparse
        torch.testing.assert_close(out.to_dense(),
                                   self._sparse().to_dense())

    def test_sum_scales_by_size(self):
        h = hvd_torch.sparse_allreduce_async(self._sparse(), name="s2",
                                             op=hvd_torch.Sum)
        out = hvd_torch.synchronize(h)
        torch.testing.assert_close(
            out.to_dense(),
            self._sparse().to_dense() * hvd_torch.size())

    def test_dense_input_rejected(self):
        with pytest.raises(ValueError, match="sparse COO"):
            hvd_torch.sparse_allreduce_async(torch.ones(3))


class TestTorchBroadcastState:
    def test_broadcast_parameters_state_dict(self):
        model = torch.nn.Linear(4, 2)
        hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)

    def test_broadcast_optimizer_state(self):
        model = torch.nn.Linear(4, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        loss = model(torch.randn(8, 4)).sum()
        loss.backward()
        opt.step()
        hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
        assert opt.param_groups[0]["lr"] == pytest.approx(0.1)

    def test_allgather_object(self):
        # One-process sim: every rank contributes this process's object,
        # so the gather is size() copies ordered by rank.
        outs = hvd_torch.allgather_object({"r": hvd_torch.rank()},
                                          name="ignored")
        assert outs == [{"r": 0}] * hvd_torch.size()

    def test_broadcast_object(self):
        obj = {"epoch": 3, "arr": [1, 2, 3]}
        assert hvd_torch.broadcast_object(obj, root_rank=0) == obj


class TestTorchDistributedOptimizer:
    def _train_once(self, bpps=1):
        torch.manual_seed(0)
        model = torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.05)
        opt = hvd_torch.DistributedOptimizer(
            opt, named_parameters=model.named_parameters(),
            backward_passes_per_step=bpps)
        x = torch.randn(16, 4)
        y = x.sum(dim=1, keepdim=True)
        losses = []
        for i in range(10 * bpps):
            if i % bpps == 0:
                opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
            opt.step()
            losses.append(float(loss.detach()))
        return losses

    def test_training_reduces_loss(self):
        losses = self._train_once()
        assert losses[-1] < losses[0] * 0.7, losses

    def test_backward_passes_per_step(self):
        losses = self._train_once(bpps=2)
        assert losses[-1] < losses[0] * 0.9, losses

    def test_duplicate_names_rejected(self):
        model = torch.nn.Linear(2, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        dup = [("same", p) for p in model.parameters()]
        with pytest.raises(ValueError):
            hvd_torch.DistributedOptimizer(opt, named_parameters=dup)

    def test_passthrough_attrs(self):
        model = torch.nn.Linear(2, 2)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1))
        assert opt.param_groups[0]["lr"] == pytest.approx(0.1)


class TestAdasumDeltaOptimizer:
    """Reference: horovod/torch/optimizer.py _DistributedAdasumOptimizer
    — local step first, Adasum on the parameter DELTA, p = start +
    adasum(deltas)."""

    def _model_opt(self, lr=0.05):
        torch.manual_seed(0)
        model = torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 1))
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=lr),
            named_parameters=model.named_parameters(),
            op=hvd_torch.Adasum)
        return model, opt

    def test_routes_to_delta_optimizer(self):
        from horovod_tpu.torch import _DistributedAdasumOptimizer
        _, opt = self._model_opt()
        assert isinstance(opt, _DistributedAdasumOptimizer)

    def test_identical_ranks_match_plain_local_step(self):
        # Every sim rank holds the same delta; adasum(identical) is the
        # identity, so the Adasum optimizer must land exactly where the
        # plain wrapped optimizer would.
        torch.manual_seed(0)
        model_a = torch.nn.Linear(4, 2)
        torch.manual_seed(0)
        model_b = torch.nn.Linear(4, 2)
        opt_a = torch.optim.SGD(model_a.parameters(), lr=0.1)
        opt_b = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model_b.parameters(), lr=0.1),
            op=hvd_torch.Adasum)
        x = torch.randn(8, 4)
        y = torch.randn(8, 2)
        for _ in range(3):
            for opt, model in ((opt_a, model_a), (opt_b, model_b)):
                opt.zero_grad()
                torch.nn.functional.mse_loss(model(x), y).backward()
                opt.step()
        for pa, pb in zip(model_a.parameters(), model_b.parameters()):
            torch.testing.assert_close(pa, pb, rtol=1e-5, atol=1e-6)

    def test_delta_algebra_p_equals_start_plus_reduced(self, monkeypatch):
        # Verify the delta recursion against the oracle model: mock the
        # reduction with an arbitrary combine (halving) and check
        # p_new == p_start + combine(p_local_step - p_start).
        model, opt = self._model_opt(lr=0.1)
        starts = [p.detach().clone() for p in model.parameters()]

        seen = {}

        def fake_reduce(deltas):
            seen["deltas"] = [d.clone() for d in deltas]
            return [d * 0.5 for d in deltas]

        monkeypatch.setattr(opt, "_reduce_deltas", fake_reduce)
        x = torch.randn(8, 4)
        y = torch.randn(8, 1)
        opt.zero_grad()
        torch.nn.functional.mse_loss(model(x), y).backward()

        # What the local step alone would produce:
        local = [
            (s - 0.1 * p.grad.detach())
            for s, p in zip(starts, model.parameters())
        ]
        opt.step()
        for p, s, lo in zip(model.parameters(), starts, local):
            torch.testing.assert_close(p.detach(), s + 0.5 * (lo - s),
                                       rtol=1e-6, atol=1e-7)
        # And the deltas fed into the reduction were the local-step deltas.
        for d, s, lo in zip(seen["deltas"], starts, local):
            torch.testing.assert_close(d, lo - s, rtol=1e-6, atol=1e-7)

    def test_start_advances_between_steps(self):
        model, opt = self._model_opt()
        x = torch.randn(8, 4)
        y = torch.randn(8, 1)
        for _ in range(2):
            opt.zero_grad()
            torch.nn.functional.mse_loss(model(x), y).backward()
            opt.step()
        for p in model.parameters():
            torch.testing.assert_close(
                opt._starting[id(p)], p.detach())

    def test_training_reduces_loss(self):
        model, opt = self._model_opt(lr=0.05)
        x = torch.randn(16, 4)
        y = x.sum(dim=1, keepdim=True)
        losses = []
        for _ in range(10):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
            opt.step()
            losses.append(float(loss.detach()))
        assert losses[-1] < losses[0] * 0.7, losses


class TestCallbacks:
    def test_metric_average(self):
        from horovod_tpu import callbacks
        out = callbacks.MetricAverageCallback().on_epoch_end(
            {"acc": np.float32(0.5)})
        assert float(out["acc"]) == pytest.approx(0.5)

    def test_broadcast_globals_once(self):
        from horovod_tpu import callbacks
        import jax.numpy as jnp
        cb = callbacks.BroadcastGlobalVariablesCallback(0)
        state = {"w": jnp.ones((3,))}
        out1 = cb.on_train_begin(state)
        out2 = cb.on_train_begin(out1)
        assert out2 is out1  # second call is a no-op
        np.testing.assert_allclose(np.asarray(out1["w"]), 1.0)

    def test_warmup_lr(self):
        from horovod_tpu import callbacks
        cb = callbacks.LearningRateWarmupCallback(5, 0.8)
        assert cb.lr(0, 10, 0) == pytest.approx(0.8 / cb.size)
        assert cb.lr(5) == pytest.approx(0.8)
        mid = cb.lr(2, 10, 5)
        assert 0.8 / cb.size < mid < 0.8

    def test_schedule_lr(self):
        from horovod_tpu import callbacks
        cb = callbacks.LearningRateScheduleCallback(
            [dict(start_epoch=0, end_epoch=2, multiplier=1.0),
             dict(start_epoch=2, end_epoch=4, multiplier=0.1),
             dict(start_epoch=4, multiplier=lambda e: 0.01)],
            initial_lr=1.0)
        assert cb.lr(1) == 1.0
        assert cb.lr(3) == pytest.approx(0.1)
        assert cb.lr(10) == pytest.approx(0.01)


class TestTrueAsync:
    """The async API must not materialize results at dispatch time
    (reference: handle_manager.cc — the handle resolves only when the
    background collective completes; here the un-materialized jax.Array
    is the in-flight state)."""

    def test_handle_holds_unmaterialized_jax_array(self):
        import jax

        t = torch.ones(8)
        h = hvd_torch.allreduce_async(t, op=hvd_torch.Sum)
        raw = hvd_torch.HandleManager.global_instance()._results[h]
        assert isinstance(raw, jax.Array)  # not a torch tensor yet
        out = hvd_torch.synchronize(h)
        assert isinstance(out, torch.Tensor)
        torch.testing.assert_close(out, t * hvd_torch.size())

    def test_poll_can_be_false_before_completion(self):
        # A large enough reduction is still in flight when dispatch
        # returns (JAX async dispatch); poll() must report that instead
        # of blocking.
        t = torch.randn(4 * 1024 * 1024)
        observed_false = False
        handles = []
        for _ in range(4):
            h = hvd_torch.allreduce_async(t)
            if not hvd_torch.poll(h):
                observed_false = True
            handles.append(h)
        for h in handles:
            hvd_torch.synchronize(h)
        assert observed_false, (
            "poll() was True immediately after every async dispatch — "
            "the API is completing synchronously")

    def test_allreduce_async_inplace(self):
        t = torch.ones(6)
        h = hvd_torch.allreduce_async_(t, op=hvd_torch.Sum)
        out = hvd_torch.synchronize(h)
        assert out is t
        torch.testing.assert_close(t, torch.full((6,), float(hvd_torch.size())))

    def test_broadcast_async(self):
        t = torch.full((3,), 7.0)
        h = hvd_torch.broadcast_async(t, root_rank=0)
        torch.testing.assert_close(hvd_torch.synchronize(h), t)


class TestHookFusion:
    """Hook-path gradients must be bucketed into fused grouped
    allreduces capped by HOROVOD_FUSION_THRESHOLD (reference: fusion
    buffer + torch/optimizer.py per-param hooks feeding it)."""

    def _run_steps(self, threshold, steps=2):
        import os

        old = os.environ.get("HOROVOD_FUSION_THRESHOLD")
        os.environ["HOROVOD_FUSION_THRESHOLD"] = str(threshold)
        try:
            torch.manual_seed(0)
            model = torch.nn.Sequential(
                torch.nn.Linear(8, 16), torch.nn.ReLU(),
                torch.nn.Linear(16, 1))  # 4 params
            opt = hvd_torch.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.01),
                named_parameters=model.named_parameters())
            x = torch.randn(4, 8)
            for _ in range(steps):
                opt.zero_grad()
                torch.nn.functional.mse_loss(
                    model(x), x.sum(1, keepdim=True)).backward()
                opt.step()
            return opt
        finally:
            if old is None:
                os.environ.pop("HOROVOD_FUSION_THRESHOLD", None)
            else:
                os.environ["HOROVOD_FUSION_THRESHOLD"] = old

    def test_large_threshold_single_bucket_per_step(self):
        opt = self._run_steps(64 * 1024 * 1024, steps=3)
        # All 4 params fit one bucket -> exactly 1 fused dispatch/step.
        assert opt.total_flushes == 3, opt.total_flushes

    def test_tiny_threshold_more_buckets(self):
        opt = self._run_steps(4, steps=1)  # every grad overflows a bucket
        assert opt.total_flushes == 4, opt.total_flushes

    def test_fp16_compression_trains(self):
        torch.manual_seed(0)
        model = torch.nn.Linear(4, 1)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters(),
            compression=hvd_torch.Compression.fp16)
        x = torch.randn(16, 4)
        y = x.sum(1, keepdim=True)
        first = last = None
        for _ in range(10):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
            opt.step()
            first = float(loss.detach()) if first is None else first
            last = float(loss.detach())
        assert last < first

    def test_each_grad_reduced_exactly_once_per_step(self, monkeypatch):
        # Regression: the step() straggler sweep must not re-enqueue
        # grads already sitting in an un-flushed hook bucket.
        import horovod_tpu.torch as ht

        counts = []
        real = ht.C.grouped_allreduce

        def counting(tensors, **kw):
            counts.append(len(tensors))
            return real(tensors, **kw)

        monkeypatch.setattr(ht.C, "grouped_allreduce", counting)
        torch.manual_seed(0)
        model = torch.nn.Sequential(
            torch.nn.Linear(8, 16), torch.nn.ReLU(),
            torch.nn.Linear(16, 1))  # 4 params
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.01),
            named_parameters=model.named_parameters())
        x = torch.randn(4, 8)
        for _ in range(2):
            opt.zero_grad()
            torch.nn.functional.mse_loss(
                model(x), x.sum(1, keepdim=True)).backward()
            opt.step()
        assert sum(counts) == 8, (counts, "expected 4 grads x 2 steps")


class TestSyncBatchNorm:
    """Reference: horovod/torch/sync_batch_norm.py — training stats are
    the global batch's.  On the sim every rank sees the same data, so
    sync stats == local stats; gradient flow and running-stat updates
    are the testable contracts."""

    def test_matches_local_bn_on_identical_data(self):
        torch.manual_seed(0)
        x = torch.randn(8, 4)
        sbn = hvd_torch.SyncBatchNorm(4)
        bn = torch.nn.BatchNorm1d(4)
        torch.testing.assert_close(sbn(x), bn(x), atol=1e-5, rtol=1e-4)
        torch.testing.assert_close(sbn.running_mean, bn.running_mean,
                                   atol=1e-5, rtol=1e-4)
        # Bessel correction uses the GLOBAL batch count (8 ranks x 8 =
        # 64) like the reference's SyncBatchNorm, so running_var differs
        # from local BN (n=8) by (64/63)/(8/7).
        # One update from init 1.0: rv = 0.9*1.0 + 0.1*unbiased_var.
        n_local, n_global = 8, 8 * hvd_torch.size()
        expected = (bn.running_var - 0.9) * \
            (n_global / (n_global - 1)) / (n_local / (n_local - 1)) + 0.9
        torch.testing.assert_close(sbn.running_var, expected,
                                   atol=1e-5, rtol=1e-4)

    def test_gradients_flow(self):
        x = torch.randn(8, 3, requires_grad=True)
        sbn = hvd_torch.SyncBatchNorm(3)
        sbn(x).sum().backward()
        assert x.grad is not None and torch.isfinite(x.grad).all()
        assert sbn.weight.grad is not None

    def test_eval_mode_uses_running_stats(self):
        sbn = hvd_torch.SyncBatchNorm(2)
        sbn(torch.randn(16, 2))  # one training step
        sbn.eval()
        out = sbn(torch.zeros(4, 2))
        assert torch.isfinite(out).all()

    def test_4d_input(self):
        x = torch.randn(4, 3, 5, 5)
        out = hvd_torch.SyncBatchNorm(3)(x)
        assert out.shape == x.shape

    def test_rejects_1d(self):
        with pytest.raises(ValueError, match="at least 2D"):
            hvd_torch.SyncBatchNorm(3)(torch.randn(3))

    def test_momentum_none_cumulative_average(self):
        # torch contract: momentum=None -> cumulative moving average,
        # same as the np=1 fallthrough path.
        sbn = hvd_torch.SyncBatchNorm(2, momentum=None)
        bn = torch.nn.BatchNorm1d(2, momentum=None)
        torch.manual_seed(0)
        for _ in range(3):
            x = torch.randn(16, 2)
            sbn(x), bn(x)
        torch.testing.assert_close(sbn.running_mean, bn.running_mean,
                                   atol=1e-5, rtol=1e-4)
        assert int(sbn.num_batches_tracked) == 3

    def test_no_nan_on_large_mean_tiny_variance(self):
        # Regression: E[x^2]-mean^2 rounds negative in f32 for constant-
        # ish channels with large mean; the clamp must prevent NaN.
        x = torch.full((32, 4), 100.0) + torch.randn(32, 4) * 1e-4
        out = hvd_torch.SyncBatchNorm(4)(x)
        assert torch.isfinite(out).all()


class TestGroupedAsync:
    def test_grouped_allreduce_async_inplace(self):
        ts = [torch.ones(3), torch.full((2,), 2.0)]
        h = hvd_torch.grouped_allreduce_async_(ts, op=hvd_torch.Sum)
        out = hvd_torch.synchronize(h)
        assert all(o is t for o, t in zip(out, ts))  # in-place contract
        n = float(hvd_torch.size())
        torch.testing.assert_close(ts[0], torch.full((3,), n))
        torch.testing.assert_close(ts[1], torch.full((2,), 2.0 * n))

    def test_grouped_allreduce_async(self):
        ts = [torch.ones(2), torch.ones(4)]
        h = hvd_torch.grouped_allreduce_async(ts)
        outs = hvd_torch.synchronize(h)
        assert isinstance(outs, list) and len(outs) == 2
        torch.testing.assert_close(outs[0], ts[0])


class TestTorchElasticState:
    """Reference: horovod/torch/elastic/state.py TorchState —
    save/restore are host-side state_dict snapshots; sync broadcasts
    from rank 0."""

    def test_save_restore_roundtrip(self):
        model = torch.nn.Linear(4, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        state = hvd_torch.elastic.TorchState(
            model=model, optimizer=opt, epoch=3, batch=7)
        saved_w = model.weight.detach().clone()
        # Corrupt everything, then restore.
        with torch.no_grad():
            model.weight.mul_(0).add_(99.0)
        state.epoch = 11
        state.restore()
        torch.testing.assert_close(model.weight.detach(), saved_w)
        assert state.epoch == 3 and state.batch == 7

    def test_commit_then_restore_keeps_committed(self):
        model = torch.nn.Linear(2, 2)
        state = hvd_torch.elastic.TorchState(model=model, epoch=0)
        with torch.no_grad():
            model.weight.fill_(5.0)
        state.epoch = 2
        state.commit()
        with torch.no_grad():
            model.weight.fill_(-1.0)
        state.restore()
        torch.testing.assert_close(
            model.weight.detach(), torch.full((2, 2), 5.0))
        assert state.epoch == 2

    def test_sync_runs(self):
        model = torch.nn.Linear(2, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        model(torch.randn(4, 2)).sum().backward()
        opt.step()
        state = hvd_torch.elastic.TorchState(
            model=model, optimizer=opt, epoch=1)
        state.sync()  # single-host: broadcast from rank 0 is identity
        assert state.epoch == 1


class TestTorchSparseAndAsync:
    def test_sparse_grads_use_sparse_allreduce_by_default(self):
        """Reference default (sparse_as_dense=False): sparse grads ride
        the allgather-based sparse allreduce; the optimizer step applies
        a sparse update and the reduced grad STAYS sparse."""
        emb = torch.nn.Embedding(8, 4, sparse=True)
        before = emb.weight.detach().clone()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(emb.parameters(), lr=0.1),
            named_parameters=emb.named_parameters())
        loss = emb(torch.tensor([1, 2])).sum()
        loss.backward()
        opt.step()
        assert emb.weight.grad.is_sparse
        after = emb.weight.detach()
        # Only the touched rows moved, by the averaged (== local, in the
        # sim) gradient of 1.0 per element: -lr * 1.
        np.testing.assert_allclose(after[1], before[1] - 0.1, atol=1e-6)
        np.testing.assert_allclose(after[2], before[2] - 0.1, atol=1e-6)
        np.testing.assert_allclose(after[0], before[0])

    def test_sparse_as_dense_trains(self):
        emb = torch.nn.Embedding(8, 4, sparse=True)
        before = emb.weight.detach().clone()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(emb.parameters(), lr=0.1),
            named_parameters=emb.named_parameters(),
            sparse_as_dense=True)
        loss = emb(torch.tensor([1, 2])).sum()
        loss.backward()
        opt.step()
        assert not torch.equal(emb.weight.detach(), before)
        assert not emb.weight.grad.is_sparse

    def test_alltoall_async(self):
        t = torch.arange(8, dtype=torch.float32)
        h = hvd_torch.alltoall_async(t)
        out = hvd_torch.synchronize(h)
        # Must agree with the synchronous op (in the sim, rank 0
        # receives every rank's slice 0).
        assert torch.equal(out, hvd_torch.alltoall(t))


class TestMoreCollectiveGradients:
    """Round out differentiability parity: reducescatter, alltoall,
    grouped allreduce, and the 0-d allgather edge."""

    def test_scalar_allgather_gradient(self):
        x = torch.tensor(2.0, requires_grad=True)
        y = hvd_torch.allgather(x)
        assert y.shape == (hvd_torch.size(),)
        y.sum().backward()
        torch.testing.assert_close(
            x.grad, torch.tensor(float(hvd_torch.size())))

    def test_scalar_allgather_no_grad(self):
        y = hvd_torch.allgather(torch.tensor(3.0))
        assert y.shape == (hvd_torch.size(),)

    def test_reducescatter_gradient_average(self):
        n = hvd_torch.size()
        x = torch.ones(2 * n, 3, requires_grad=True)
        y = hvd_torch.reducescatter(x)
        y.sum().backward()
        torch.testing.assert_close(x.grad,
                                   torch.full((2 * n, 3), 1.0 / n))

    def test_alltoall_gradient(self):
        n = hvd_torch.size()
        x = torch.ones(n, 2, requires_grad=True)
        y = hvd_torch.alltoall(x * 3.0)
        y.sum().backward()
        torch.testing.assert_close(x.grad, torch.full((n, 2), 3.0))

    def test_grouped_allreduce_gradient(self):
        a = torch.ones(3, requires_grad=True)
        b = torch.ones(2, 2, requires_grad=True)
        outs = hvd_torch.grouped_allreduce([a * 2.0, b * 5.0])
        (outs[0].sum() + outs[1].sum()).backward()
        torch.testing.assert_close(a.grad, torch.full((3,), 2.0))
        torch.testing.assert_close(b.grad, torch.full((2, 2), 5.0))


class TestTorchPredivide:
    def test_predivide_matches_plain_average(self):
        def train_once(**kw):
            torch.manual_seed(0)
            net = torch.nn.Linear(4, 2)
            opt = hvd_torch.DistributedOptimizer(
                torch.optim.SGD(net.parameters(), lr=0.1),
                named_parameters=net.named_parameters(), **kw)
            x = torch.randn(8, 4, generator=torch.Generator().manual_seed(1))
            loss = net(x).pow(2).mean()
            opt.zero_grad()
            loss.backward()
            opt.step()
            return [p.detach().clone() for p in net.parameters()]

        plain = train_once()
        pre = train_once(gradient_predivide_factor=4.0)
        for a, b in zip(plain, pre):
            torch.testing.assert_close(a, b, rtol=1e-5, atol=1e-6)

    def test_predivide_requires_average(self):
        net = torch.nn.Linear(2, 1)
        with pytest.raises(ValueError, match="requires op=Average"):
            hvd_torch.DistributedOptimizer(
                torch.optim.SGD(net.parameters(), lr=0.1),
                op=hvd_torch.Sum, gradient_predivide_factor=2.0)
