"""Native (C++) control-plane and timeline-writer tests.

The rendezvous wire-protocol tests in test_runner.py already run
parametrized over both engines; these cover the native-only surfaces:
builds/loads, timeline output validity, and concurrent server load.
"""

import json
import threading

import pytest

from horovod_tpu._native import load


pytestmark = pytest.mark.skipif(load() is None,
                                reason="native library unavailable")


class TestNativeTimeline:
    def test_writer_strict_json(self, tmp_path):
        from horovod_tpu._native.control_plane import NativeTimelineWriter
        path = tmp_path / "trace.json"
        w = NativeTimelineWriter(str(path))
        w.event("ALLREDUCE", "collective", "X", ts_us=10.0, dur_us=5.5,
                pid=3, tid="grad/dense0")
        w.event("CYCLE_1", "cycle", "i", ts_us=20.0, scope="p")
        w.event("with args", "event", "i", ts_us=30.0,
                args_json='{"k": "v"}')
        w.close()
        events = json.loads(path.read_text())
        assert len(events) == 3
        assert events[0] == {"name": "ALLREDUCE", "cat": "collective",
                             "ph": "X", "ts": 10.0, "dur": 5.5, "pid": 3,
                             "tid": "grad/dense0"}
        assert events[1]["s"] == "p"
        assert events[2]["args"] == {"k": "v"}

    def test_escaping(self, tmp_path):
        from horovod_tpu._native.control_plane import NativeTimelineWriter
        path = tmp_path / "trace.json"
        w = NativeTimelineWriter(str(path))
        w.event('quote"back\\slash\nnewline', "c", "i", ts_us=1.0,
                tid="tab\there")
        w.close()
        events = json.loads(path.read_text())
        assert events[0]["name"] == 'quote"back\\slash\nnewline'
        assert events[0]["tid"] == "tab\there"

    def test_timeline_class_uses_native(self, tmp_path):
        from horovod_tpu.utils.timeline import Timeline, _NativeWriterAdapter
        path = tmp_path / "t.json"
        tl = Timeline(str(path), rank=1)
        assert isinstance(tl._writer, _NativeWriterAdapter)
        tok = tl.activity_start("tensor.a", "ALLREDUCE")
        tl.activity_end(tok)
        tl.instant("note", args={"x": 1})
        tl.close()
        events = json.loads(path.read_text())
        assert [e["name"] for e in events] == ["ALLREDUCE", "note"]
        assert events[0]["pid"] == 1


class TestNativeServerLoad:
    def test_many_concurrent_clients(self):
        from horovod_tpu.runner.rendezvous import (
            RendezvousClient,
            RendezvousServer,
        )
        srv = RendezvousServer(prefer_native=True)
        port = srv.start()
        assert srv._native is not None
        n = 16
        errors = []

        def worker(i):
            try:
                c = RendezvousClient("127.0.0.1", port, srv.secret)
                for j in range(20):
                    c.put(f"k/{i}/{j}", f"v{i * 100 + j}")
                c.barrier("load", n, timeout=30)
                # Every client sees every key after the barrier.
                assert len(c.keys("k/")) == n * 20
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        srv.stop()
        assert not errors, errors

    def test_kv_facade(self):
        from horovod_tpu.runner.rendezvous import RendezvousServer
        srv = RendezvousServer(prefer_native=True)
        srv.start()
        kv = srv.kv()
        kv.put("a", "1")
        assert kv.get("a") == "1"
        assert kv.wait("a", timeout=1) == "1"
        assert kv.wait("missing", timeout=0.2) is None
        assert kv.delete("a") and not kv.delete("a")
        srv.stop()
