"""Flagship transformer LM over a hybrid mesh (beyond-parity example).

Demonstrates composing every parallelism axis the framework supports —
data, tensor, sequence (ring attention or Ulysses), expert (MoE), and
pipeline — on synthetic token data.

Run:  python examples/transformer_lm.py --dp 1                 # 1 chip
      python examples/transformer_lm.py --dp 2 --tp 2 --sp 2   # 8 devices
      python examples/transformer_lm.py --dp 2 --pp 2 --ep 2 --moe-every 2
"""

import argparse
import sys
import time

import jax
import numpy as np
import optax

from horovod_tpu.models import (
    TransformerConfig,
    make_train_step,
    stack_for_pipeline,
    transformer_init,
)
from horovod_tpu.parallel import create_hybrid_mesh


def main():
    p = argparse.ArgumentParser()
    for axis in ("dp", "tp", "pp", "ep", "sp"):
        p.add_argument(f"--{axis}", type=int, default=1)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--moe-every", type=int, default=0)
    p.add_argument("--attn", choices=["ring", "ulysses"], default="ring")
    p.add_argument("--n-kv-heads", type=int, default=0,
                   help="GQA/MQA kv head count (0 = MHA)")
    p.add_argument("--attn-window", type=int, default=0,
                   help="causal sliding window (0 = full)")
    p.add_argument("--steps", type=int, default=20)
    args = p.parse_args()

    mesh = create_hybrid_mesh(dp=args.dp, tp=args.tp, pp=args.pp,
                              ep=args.ep, sp=args.sp)
    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        d_head=args.d_model // args.n_heads, d_ff=args.d_model * 4,
        n_layers=args.n_layers, moe_every=args.moe_every,
        attn_impl=args.attn, n_kv_heads=args.n_kv_heads,
        attn_window=args.attn_window)

    params = transformer_init(jax.random.PRNGKey(0), cfg)
    params = stack_for_pipeline(params, args.pp, cfg)
    opt = optax.adamw(3e-4)
    step, shard_state, shard_batch = make_train_step(mesh, cfg, opt)
    params, opt_state = shard_state(params, opt.init(params))

    rng = np.random.RandomState(0)
    toks = rng.randint(0, args.vocab,
                       size=(args.batch_size, args.seq_len + 1))
    batch = shard_batch((toks[:, :-1], toks[:, 1:]))

    params, opt_state, loss = step(params, opt_state, batch)  # compile
    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, batch)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / args.steps
    tokens_per_sec = args.batch_size * args.seq_len / dt
    print(f"mesh dp{args.dp}/tp{args.tp}/pp{args.pp}/ep{args.ep}/"
          f"sp{args.sp}: loss={float(loss):.4f} "
          f"{dt * 1e3:.1f} ms/step {tokens_per_sec:,.0f} tok/s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
