"""Programmatic worker-pool example (reference: horovod/ray examples —
RayExecutor.start/run/shutdown, here on the built-in process pool).

A persistent 2-worker pool runs several functions without relaunching:
an env probe, then a real cross-process allreduce.

Run:  python examples/executor_pool.py [--np 2]
"""

import argparse
import os


def probe():
    return {
        "rank": int(os.environ["HOROVOD_RANK"]),
        "size": int(os.environ["HOROVOD_SIZE"]),
        "pid": os.getpid(),
    }


def train_step(scale):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    grad = np.full((4,), float(hvd.rank() + 1) * scale, np.float32)
    avg = hvd.allreduce(grad)
    return float(np.asarray(avg)[0])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--np", type=int, default=2)
    args = p.parse_args()

    os.environ.pop("XLA_FLAGS", None)  # one CPU device per worker
    from horovod_tpu.runner.executor import Executor

    with Executor(np=args.np) as ex:
        print("probe:", ex.run(probe))
        avgs = ex.run(train_step, args=(10.0,), timeout=240)
        print("allreduced gradients per rank:", avgs)
        expected = 10.0 * (args.np + 1) / 2
        assert all(abs(a - expected) < 1e-5 for a in avgs), avgs
        print("pool reused across", 2, "dispatches — OK")


if __name__ == "__main__":
    main()
