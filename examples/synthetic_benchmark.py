"""ResNet synthetic benchmark (BASELINE config 2; config 4 via --use-adasum).

Mirrors the reference's `examples/pytorch/pytorch_synthetic_benchmark.py`:
synthetic ImageNet-shaped data, SGD, timed iterations, img/sec with
stddev, total img/sec across ranks — the headline Horovod number.

Run:  python examples/synthetic_benchmark.py --model resnet50 --num-iters 5
      python examples/synthetic_benchmark.py --use-adasum
"""

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import zoo_apply, zoo_init, zoo_models


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50", choices=zoo_models())
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-warmup-batches", type=int, default=2)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--image-size", type=int, default=None,
                   help="default: 299 for inception3 (its canonical "
                        "benchmark size), 224 otherwise")
    p.add_argument("--use-adasum", action="store_true",
                   help="Adasum gradient aggregation (reference "
                        "--use-adasum)")
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="fp16 wire compression (reference --fp16-allreduce)")
    p.add_argument("--compression", default=None,
                   choices=["fp16", "bf16", "int8", "fp8_e4m3",
                            "fp8_e5m2"],
                   help="gradient wire compression; int8/fp8 use the "
                        "quantized ring collective (ops/quantized.py)")
    args = p.parse_args()
    if args.image_size is None:
        args.image_size = 299 if args.model == "inception3" else 224

    hvd.init()
    init_kwargs = ({"image_size": args.image_size}
                   if args.model == "vgg16" else {})
    v = zoo_init(args.model, jax.random.PRNGKey(0), num_classes=1000,
                 **init_kwargs)
    model_apply = zoo_apply(args.model)
    cfg = v["config"]
    state = {"params": v["params"], "batch_stats": v["batch_stats"]}

    from horovod_tpu.ops.compression import _CooperativeCompressor

    if args.compression:
        compression = getattr(hvd.Compression, args.compression)
    else:
        compression = (hvd.Compression.fp16 if args.fp16_allreduce
                       else hvd.Compression.none)
    cooperative = (isinstance(compression, type) and
                   issubclass(compression, _CooperativeCompressor))
    if args.use_adasum and cooperative:
        p.error("--use-adasum bypasses gradient allreduce (it reduces "
                "deltas), so 1-byte ring compression does not apply; "
                "pick one")
    op = hvd.Adasum if args.use_adasum else hvd.Average
    # 1-byte ring formats need the mesh axis (in-jit path).
    axis_kw = {"axis_name": hvd.GLOBAL_AXIS} if cooperative else {}
    opt = hvd.DistributedOptimizer(
        optax.sgd(0.01 * (1 if args.use_adasum else hvd.size()),
                  momentum=0.9),
        op=op, compression=compression, **axis_kw)
    opt_state = opt.init(state["params"])
    state["params"] = hvd.broadcast_parameters(state["params"], root_rank=0)

    x = jnp.asarray(np.random.rand(
        args.batch_size * hvd.local_size(), args.image_size,
        args.image_size, 3).astype(np.float32))
    y = jnp.asarray(np.random.randint(
        0, 1000, size=args.batch_size * hvd.local_size()))

    @hvd.data_parallel
    def step(state, opt_state, batch):
        xb, yb = batch

        def loss_fn(p):
            logits, ns = model_apply(
                {"params": p, "batch_stats": state["batch_stats"],
                 "config": cfg},
                xb, train=True, compute_dtype=jnp.bfloat16,
                axis_name=hvd.GLOBAL_AXIS)
            onehot = jax.nn.one_hot(yb, 1000)
            loss = -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
            return loss, ns

        (loss, ns), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"])
        updates, opt_state2 = opt.update(grads, opt_state, state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "batch_stats": ns}, opt_state2, loss

    batch = hvd.shard_batch((x, y))

    def run_batches(n):
        nonlocal state, opt_state
        for _ in range(n):
            state, opt_state, loss = step(state, opt_state, batch)
        jax.block_until_ready(loss)

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch {args.batch_size}/rank, "
              f"{hvd.size()} rank(s)", flush=True)
    run_batches(args.num_warmup_batches)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.time()
        run_batches(args.num_batches_per_iter)
        dt = time.time() - t0
        img_sec = args.batch_size * args.num_batches_per_iter * \
            hvd.local_size() / dt
        if hvd.rank() == 0:
            print(f"Iter #{i}: {img_sec:.1f} img/sec per process",
                  flush=True)
        img_secs.append(img_sec)

    if hvd.rank() == 0:
        mean, std = np.mean(img_secs), np.std(img_secs)
        print(f"Img/sec per process: {mean:.1f} +- {1.96 * std:.1f}")
        print(f"Total img/sec on {hvd.size()} rank(s): "
              f"{mean * hvd.num_processes():.1f} +- "
              f"{1.96 * std * hvd.num_processes():.1f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
