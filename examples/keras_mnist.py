"""TF2 Keras MNIST (the reference's tensorflow2_keras_mnist.py, verbatim
flow, through `horovod_tpu.tensorflow.keras`) — BASELINE.md config 3.

The model runs in CPU TensorFlow; gradient allreduce and variable
broadcast run through the XLA collective core.

Run:  python examples/keras_mnist.py [--epochs 1]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow.keras as hvd
from examples.mnist import synthetic_mnist


def build_model():
    """The reference example's conv net (tensorflow2_keras_mnist.py)."""
    return tf.keras.Sequential([
        tf.keras.layers.Input(shape=(28, 28, 1)),
        tf.keras.layers.Conv2D(32, [3, 3], activation="relu"),
        tf.keras.layers.MaxPooling2D(pool_size=(2, 2)),
        tf.keras.layers.Conv2D(64, [3, 3], activation="relu"),
        tf.keras.layers.MaxPooling2D(pool_size=(2, 2)),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dropout(0.25),
        tf.keras.layers.Dense(10, activation="softmax"),
    ])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--n", type=int, default=512, help="synthetic samples")
    p.add_argument("--base-lr", type=float, default=0.001)
    args = p.parse_args()

    hvd.init()

    x, y = synthetic_mnist(args.n, seed=hvd.rank())
    x = x.reshape(-1, 28, 28, 1).astype(np.float32)
    y = y.astype(np.int32)

    model = build_model()
    # Reference recipe: scale LR by size, wrap the optimizer, broadcast
    # initial state, average logged metrics.
    scaled_lr = args.base_lr * hvd.size()
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.Adam(learning_rate=scaled_lr))
    model.compile(
        optimizer=opt,
        loss=tf.keras.losses.SparseCategoricalCrossentropy(),
        metrics=["accuracy"],
    )
    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            initial_lr=scaled_lr, warmup_epochs=1),
    ]
    hist = model.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
                     callbacks=callbacks, verbose=2 if hvd.rank() == 0 else 0)
    if hvd.rank() == 0:
        print(f"final loss: {hist.history['loss'][-1]:.4f}")
    return hist.history["loss"]


if __name__ == "__main__":
    main()
