"""Spark Estimator demo (the reference's
examples/spark/keras/keras_spark_mnist.py flow, condensed): DataFrame in,
distributed fit across workers, Transformer out.

Works WITHOUT Spark — a pandas DataFrame trains through real local
worker processes (the LocalBackend); with pyspark installed and a
SparkSession active, the same code runs on barrier tasks.

Run:  python examples/spark_estimator.py [--np 2] [--framework torch|keras]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd


def make_dataframe(n=256, seed=0):
    """Tiny regression set: y = 2*a - b + 0.5 + noise."""
    rng = np.random.default_rng(seed)
    a = rng.normal(size=n).astype(np.float32)
    b = rng.normal(size=n).astype(np.float32)
    y = 2 * a - b + 0.5 + 0.05 * rng.normal(size=n).astype(np.float32)
    return pd.DataFrame({"a": a, "b": b, "y": y})


def run_torch(df, np_workers):
    import torch

    from horovod_tpu.spark.common import LocalBackend
    from horovod_tpu.spark.torch import TorchEstimator

    torch.manual_seed(0)  # model INIT must be seeded too, not just training
    net = torch.nn.Sequential(torch.nn.Linear(2, 16), torch.nn.ReLU(),
                              torch.nn.Linear(16, 1))
    est = TorchEstimator(
        model=net,
        optimizer=torch.optim.Adam(net.parameters(), lr=0.01),
        loss=torch.nn.functional.mse_loss,
        feature_cols=["a", "b"], label_cols=["y"],
        batch_size=32, epochs=20, validation=0.2, random_seed=0,
        backend=LocalBackend(np_workers, start_timeout=300))
    model = est.fit(df)
    return model, model.get_history()["loss"]


def run_keras(df, np_workers):
    import tensorflow as tf

    from horovod_tpu.spark.common import LocalBackend
    from horovod_tpu.spark.keras import KerasEstimator

    tf.keras.utils.set_random_seed(0)  # seed the model init too
    m = tf.keras.Sequential([
        tf.keras.layers.Input((2,)),
        tf.keras.layers.Dense(16, activation="relu"),
        tf.keras.layers.Dense(1),
    ])
    est = KerasEstimator(
        model=m, optimizer=tf.keras.optimizers.Adam(0.01), loss="mse",
        feature_cols=["a", "b"], label_cols=["y"],
        batch_size=32, epochs=20, validation=0.2, random_seed=0,
        backend=LocalBackend(np_workers, start_timeout=300))
    model = est.fit(df)
    return model, model.get_history()["loss"]


def run_lightning(df, np_workers):
    """The LightningModule-contract path (horovod_tpu.spark.lightning):
    no estimator-level loss/optimizer — the module supplies both."""
    import torch

    from examples.lit_module import LitRegressor
    from horovod_tpu.spark.common import LocalBackend
    from horovod_tpu.spark.lightning import LightningEstimator

    # Workers unpickle the module by class reference.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.environ["PYTHONPATH"] = (
        repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    torch.manual_seed(0)
    est = LightningEstimator(
        model=LitRegressor(lr=0.01),
        feature_cols=["a", "b"], label_cols=["y"],
        batch_size=32, epochs=20, validation=0.2, random_seed=0,
        backend=LocalBackend(np_workers, start_timeout=300))
    model = est.fit(df)
    return model, model.get_history()["loss"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--np", type=int, default=2)
    ap.add_argument("--framework", choices=["torch", "keras", "lightning"],
                    default="torch")
    args = ap.parse_args()

    df = make_dataframe()
    runner = {"torch": run_torch, "keras": run_keras,
              "lightning": run_lightning}[args.framework]
    model, losses = runner(df, args.np)
    out = model.transform(df)
    preds = np.asarray([float(np.ravel(v)[0]) for v in out["prediction"]])
    mse = float(np.mean((preds - df["y"].to_numpy()) ** 2))
    print(f"loss curve: {[round(v, 4) for v in losses]}")
    print(f"transform mse: {mse:.4f}")
    assert losses[-1] < losses[0] and mse < 0.2
    print("ok")


if __name__ == "__main__":
    main()
