"""KV-cache text generation on the flagship transformer.

Completes the model family's inference path (the reference has no
generation at all): scan-compiled incremental decode with a GQA-sized
cache and optional sliding-window attention.

    python examples/generate.py --n-kv-heads 2 --attn-window 64 \
        --prompt-len 8 --new-tokens 32
"""

import argparse
import time

import jax
import jax.numpy as jnp

from horovod_tpu.models import (
    TransformerConfig,
    transformer_beam_search,
    transformer_generate,
    transformer_init,
    transformer_speculative_generate,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-kv-heads", type=int, default=0)
    p.add_argument("--attn-window", type=int, default=0)
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--new-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--eos-id", type=int, default=-1,
                   help="stop token: tails after the first eos read "
                        "eos (generate + beam; -1 = off)")
    p.add_argument("--beam", type=int, default=0,
                   help="beam width (0 = greedy/sampling path)")
    p.add_argument("--spec-gamma", type=int, default=0,
                   help="speculative decoding: draft proposals per "
                        "round (0 = off; batched rows advance by the "
                        "batch-minimum acceptance)")
    p.add_argument("--draft-d-model", type=int, default=64,
                   help="draft model width for --spec-gamma")
    p.add_argument("--draft-layers", type=int, default=1)
    args = p.parse_args()

    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        d_head=args.d_model // args.n_heads, d_ff=4 * args.d_model,
        n_layers=args.n_layers, n_kv_heads=args.n_kv_heads,
        attn_window=args.attn_window)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1),
                                (args.batch, args.prompt_len), 0, cfg.vocab_size)

    if (args.top_p < 1.0 or args.top_k) and not args.temperature:
        raise SystemExit(
            "--top-p/--top-k need --temperature > 0 (greedy decoding "
            "ignores them)")
    if args.beam and (args.temperature or args.top_p < 1.0 or args.top_k):
        raise SystemExit(
            "--beam is deterministic; drop --temperature/--top-p/--top-k")
    rng = jax.random.PRNGKey(2) if args.temperature else None
    eos = args.eos_id if args.eos_id >= 0 else None
    t0 = time.perf_counter()
    if args.spec_gamma:
        if eos is not None:
            raise SystemExit("--eos-id is not supported with --spec-gamma")
        if args.beam:
            raise SystemExit("--spec-gamma and --beam are exclusive")
        if args.top_p < 1.0 or args.top_k:
            raise SystemExit(
                "--top-p/--top-k are not supported with --spec-gamma "
                "(the speculative accept rule samples the full "
                "distribution)")
        if args.attn_window:
            raise SystemExit(
                "--attn-window is not supported with --spec-gamma "
                "(rollback across a rolling ring would evict live slots)")
        draft_cfg = TransformerConfig(
            vocab_size=args.vocab, d_model=args.draft_d_model,
            n_heads=max(1, args.draft_d_model // 32),
            d_head=min(32, args.draft_d_model),
            d_ff=4 * args.draft_d_model, n_layers=args.draft_layers)
        draft = transformer_init(jax.random.PRNGKey(9), draft_cfg)
        out, stats = transformer_speculative_generate(
            params, cfg, draft, draft_cfg, prompt, args.new_tokens,
            gamma=args.spec_gamma, temperature=args.temperature, rng=rng)
        dt = time.perf_counter() - t0
        n = args.batch * args.new_tokens
        print(f"speculative gamma={args.spec_gamma}: "
              f"{n} tokens in {dt:.2f}s; accept rate "
              f"{stats['accept_rate']:.2f} over {stats['rounds']} rounds")
        print("first sequence:", out[0].tolist())
        return
    if args.beam:
        out, scores = transformer_beam_search(
            params, cfg, prompt, args.new_tokens, beam_width=args.beam,
            eos_id=eos)
        out.block_until_ready()
        dt = time.perf_counter() - t0
        n = args.batch * args.new_tokens * args.beam
        print(f"beam {args.beam}: {n} tokens in {dt:.2f}s; best score "
              f"{float(scores[0, 0]):.3f}")
        print("best sequence:", out[0, 0].tolist())
    else:
        out, cache = transformer_generate(
            params, cfg, prompt, args.new_tokens,
            temperature=args.temperature, top_p=args.top_p,
            top_k=args.top_k, eos_id=eos, rng=rng)
        out.block_until_ready()
        dt = time.perf_counter() - t0
        n = args.batch * args.new_tokens
        print(f"generated {n} tokens in {dt:.2f}s "
              f"({n / dt:.0f} tok/s incl. compile); cache pos "
              f"{int(cache['pos'])}, kv heads {cfg.kv_heads}")
        print("first sequence:", out[0].tolist())


if __name__ == "__main__":
    main()
