"""Data-parallel MNIST training (BASELINE config 1).

Mirrors the reference's `examples/pytorch/pytorch_mnist.py` flow with the
JAX-native API: init → shard batches → DistributedOptimizer → broadcast
initial params → train/test loops with metric averaging.

This image has no network access, so the MNIST tensors are synthesized
(deterministic digit-like blobs); swap `synthetic_mnist` for a real
loader outside the sandbox.

Run:  python examples/mnist.py [--epochs 3]
      horovodrun_tpu -np 1 python examples/mnist.py
"""

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import mnist_cnn_apply, mnist_cnn_init, nll_loss


def synthetic_mnist(n=8192, seed=0):
    """Digit-like synthetic data: each class is a fixed blob + noise."""
    rng = np.random.RandomState(seed)
    protos = rng.rand(10, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, size=n)
    images = protos[labels] + 0.3 * rng.randn(n, 28, 28).astype(np.float32)
    return images[..., None], labels


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.5)
    args = p.parse_args()

    hvd.init()
    np.random.seed(42)

    images, labels = synthetic_mnist()
    n_test = len(images) // 8
    test_x, test_y = images[:n_test], labels[:n_test]
    train_x, train_y = images[n_test:], labels[n_test:]

    params = mnist_cnn_init(jax.random.PRNGKey(0))
    # Scale LR by size (reference does the same) and wrap the optimizer.
    opt = hvd.DistributedOptimizer(
        optax.sgd(args.lr * hvd.size(), momentum=args.momentum))
    opt_state = opt.init(params)
    # All ranks start from rank 0's weights.
    params = hvd.broadcast_parameters(params, root_rank=0)

    @hvd.data_parallel
    def train_step(params, opt_state, batch):
        x, y = batch

        def loss_fn(p):
            logits = mnist_cnn_apply(p, x)
            return nll_loss(logits, y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    @hvd.data_parallel
    def eval_step(params, batch):
        x, y = batch
        logits = mnist_cnn_apply(params, x)
        return jnp.mean(jnp.argmax(logits, -1) == y)

    global_bs = args.batch_size * hvd.size()
    steps = len(train_x) // global_bs
    for epoch in range(args.epochs):
        t0 = time.time()
        perm = np.random.permutation(len(train_x))

        def host_batches():
            for i in range(steps):
                idx = perm[i * global_bs:(i + 1) * global_bs]
                yield train_x[idx], train_y[idx]

        # Double-buffered host->device pipeline: batch i+1 transfers
        # while batch i trains (utils/prefetch.py).
        for batch in hvd.prefetch_to_device(host_batches(), size=2):
            params, opt_state, loss = train_step(params, opt_state, batch)
        # Metric averaging across ranks (reference: MetricAverageCallback).
        acc = eval_step(params, hvd.shard_batch(
            (test_x[:global_bs * 4], test_y[:global_bs * 4])))
        acc = hvd.allreduce(acc, op=hvd.Average)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(loss):.4f} "
                  f"test_acc={float(acc):.3f} "
                  f"({time.time() - t0:.1f}s)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
