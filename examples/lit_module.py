"""LightningModule-contract regression model for the estimator demo.

Lives in its own importable module (not the example's __main__) because
the fitted module pickles by class reference and must deserialize
inside the spawned worker processes.  With pytorch_lightning installed
this class could equally subclass pl.LightningModule — the estimator
drives exactly this method surface either way.
"""

import torch


class LitRegressor(torch.nn.Module):
    def __init__(self, lr=0.01):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(2, 16), torch.nn.ReLU(),
            torch.nn.Linear(16, 1))
        self.lr = lr

    def forward(self, x):
        return self.net(x)

    def configure_optimizers(self):
        return torch.optim.Adam(self.parameters(), lr=self.lr)

    def training_step(self, batch, batch_idx):
        x, y = batch
        return torch.nn.functional.mse_loss(self(x), y)

    def validation_step(self, batch, batch_idx):
        x, y = batch
        return torch.nn.functional.mse_loss(self(x), y)
