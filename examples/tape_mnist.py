"""DistributedGradientTape MNIST (BASELINE config 3).

Mirrors the reference's `examples/tensorflow2/tensorflow2_keras_mnist.py`
pattern — tape-style gradients with per-call allreduce instead of an
optimizer wrapper — using the JAX-native `DistributedGradientTape`
equivalent and the keras-style callbacks.

Run:  python examples/tape_mnist.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import mnist_cnn_apply, mnist_cnn_init, nll_loss
from examples.mnist import synthetic_mnist


def main():
    hvd.init()
    images, labels = synthetic_mnist(4096)

    params = mnist_cnn_init(jax.random.PRNGKey(0))
    opt = optax.adam(0.001 * hvd.size())
    opt_state = opt.init(params)

    # Reference: BroadcastGlobalVariablesCallback(0) on train begin.
    bcast = hvd.callbacks.BroadcastGlobalVariablesCallback(0)
    params = bcast.on_train_begin(params)
    warmup = hvd.callbacks.LearningRateWarmupCallback(
        warmup_epochs=1, initial_lr=0.001 * hvd.size())
    metric_avg = hvd.callbacks.MetricAverageCallback()

    tape = hvd.DistributedGradientTape()

    @hvd.data_parallel
    def train_step(params, opt_state, batch):
        x, y = batch
        loss, grads = tape.gradient(
            lambda p: nll_loss(mnist_cnn_apply(p, x), y), params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    global_bs = 64 * hvd.size()
    for epoch in range(2):
        _ = warmup.lr(epoch)  # feed into optax schedule in real use
        perm = np.random.RandomState(epoch).permutation(len(images))
        for i in range(len(images) // global_bs):
            idx = perm[i * global_bs:(i + 1) * global_bs]
            batch = hvd.shard_batch((images[idx], labels[idx]))
            params, opt_state, loss = train_step(params, opt_state, batch)
        metrics = metric_avg.on_epoch_end({"loss": loss})
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(metrics['loss']):.4f}",
                  flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
