"""Autotune demo: the GP/EI parameter manager driving live throughput.

Reference parity: `horovodrun --autotune` tunes the fusion threshold and
cycle time from online throughput samples
(`horovod/common/parameter_manager.cc`, `optim/bayesian_optimization.cc`).
In this framework the fusion-threshold knob is live-wired the same way
(`utils/autotune.py init_from_env` + `parallel/data_parallel.py`), and is
integration-tested on the simulated multi-rank mesh — but fusion only
matters when there ARE cross-rank collectives.  On a single chip the
honest demonstration of the same machinery is a knob whose effect is
measurable there: this script lets the ParameterManager search the
per-chip batch size of the ResNet synthetic step for maximum img/s,
converging toward the plateau the hand sweep found (batch ~128-256 on
v5e, docs/PERF_NOTES.md).

Each proposal triggers a retrace/recompile — exactly the cost profile
the real fusion knob has (`on_change` → program-cache invalidation), so
the demo exercises the full loop: propose → recompile → measure →
observe → freeze at best.

Run:  python examples/autotune_demo.py                 # real chip
      python examples/autotune_demo.py --tiny          # CPU smoke run
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import zoo_apply, zoo_init
from horovod_tpu.utils.autotune import ParameterManager


def snap(b: int, quantum: int = 32) -> int:
    """MXU-friendly batch: multiples of 32 (sublane x lane tiling); also
    collapses nearby GP proposals onto one compiled program."""
    return max(quantum, int(round(b / quantum)) * quantum)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--low", type=int, default=32)
    p.add_argument("--high", type=int, default=512)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--steps-per-sample", type=int, default=5)
    p.add_argument("--max-samples", type=int, default=10)
    p.add_argument("--warmup-samples", type=int, default=1)
    p.add_argument("--log-file", default=None,
                   help="CSV log (the HOROVOD_AUTOTUNE_LOG format)")
    p.add_argument("--tiny", action="store_true",
                   help="mnist-scale smoke config for CPU runs/tests")
    args = p.parse_args()
    if args.tiny:
        args.model = "resnet18"
        args.image_size = 32
        args.low, args.high = 8, 64
        args.steps_per_sample = 2
        args.max_samples = 3
        args.warmup_samples = 1

    hvd.init()
    num_classes = 10 if args.tiny else 1000
    v = zoo_init(args.model, jax.random.PRNGKey(0),
                 num_classes=num_classes)
    model_apply = zoo_apply(args.model)
    cfg = v["config"]
    opt = optax.sgd(0.01, momentum=0.9)

    def make_step():
        @jax.jit
        def step(params, batch_stats, opt_state, xb, yb):
            def loss_fn(p):
                logits, ns = model_apply(
                    {"params": p, "batch_stats": batch_stats,
                     "config": cfg},
                    xb, train=True, compute_dtype=jnp.bfloat16)
                onehot = jax.nn.one_hot(yb, num_classes)
                loss = -jnp.mean(
                    jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
                return loss, ns

            (loss, ns), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state2 = opt.update(updates=grads,
                                             state=opt_state,
                                             params=params)
            params2 = optax.apply_updates(params, updates)
            return params2, ns, opt_state2, loss

        return step

    step = make_step()
    rng = np.random.default_rng(0)
    chan = 3

    def measure(batch: int) -> float:
        """img/s of `steps_per_sample` steps at this batch (jit cache
        makes repeat visits to a batch size compile-free)."""
        x = jnp.asarray(rng.random(
            (batch, args.image_size, args.image_size, chan),
            dtype=np.float32))
        y = jnp.asarray(rng.integers(0, num_classes, size=batch))
        params, bs = v["params"], v["batch_stats"]
        opt_state = opt.init(params)
        # one untimed step: compile + warm caches for this shape
        params, bs, opt_state, loss = step(params, bs, opt_state, x, y)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(args.steps_per_sample):
            params, bs, opt_state, loss = step(params, bs, opt_state, x, y)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        return batch * args.steps_per_sample / dt

    pm = ParameterManager(warmup_samples=args.warmup_samples,
                          steps_per_sample=1,  # we report whole samples
                          max_samples=args.max_samples,
                          log_file=args.log_file)
    pm.register("batch", args.low, args.high, log_scale=True,
                integer=True, initial=snap((args.low + args.high) // 4))

    history = []
    while not pm.frozen:
        b = snap(int(pm.value("batch")), 8 if args.tiny else 32)
        rate = measure(b)
        history.append((b, rate))
        print(f"sample {len(history):2d}: batch {b:4d} -> "
              f"{rate:8.1f} img/s", flush=True)
        pm.record_sample(rate)

    best_b, best_rate = max(history, key=lambda h: h[1])
    print(f"frozen: manager value {int(pm.value('batch'))} "
          f"(snapped {snap(int(pm.value('batch')), 8 if args.tiny else 32)}); "
          f"best measured batch {best_b} at {best_rate:.1f} img/s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
