"""PyTorch-frontend MNIST (the reference's pytorch_mnist.py, verbatim
flow, through `horovod_tpu.torch`).

The model/backward run in CPU PyTorch; gradient allreduce and parameter
broadcast run through the XLA collective core.

Run:  python examples/torch_mnist.py [--epochs 1]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd
from examples.mnist import synthetic_mnist


class Net(torch.nn.Module):
    """The reference example's conv net (pytorch_mnist.py `Net`)."""

    def __init__(self):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = torch.nn.Conv2d(10, 20, kernel_size=5)
        self.conv2_drop = torch.nn.Dropout2d()
        self.fc1 = torch.nn.Linear(320, 50)
        self.fc2 = torch.nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2_drop(self.conv2(x)), 2))
        x = x.view(-1, 320)
        x = F.relu(self.fc1(x))
        x = F.dropout(x, training=self.training)
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42 + hvd.rank())

    images, labels = synthetic_mnist(2048)
    x = torch.from_numpy(images.transpose(0, 3, 1, 2).copy())
    y = torch.from_numpy(labels)

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(),
                                lr=args.lr * hvd.size(), momentum=0.5)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    model.train()
    n = len(x) // args.batch_size
    for epoch in range(args.epochs):
        for i in range(n):
            s = slice(i * args.batch_size, (i + 1) * args.batch_size)
            optimizer.zero_grad()
            loss = F.nll_loss(model(x[s]), y[s])
            loss.backward()
            optimizer.step()
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(loss.detach()):.4f}",
                  flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
