"""Elastic ResNet training (BASELINE config 5).

Mirrors the reference's `examples/elastic/pytorch/pytorch_resnet_elastic
.py`: state commit/restore/sync around a training loop that survives
worker join/leave.

Run under the elastic launcher:
    horovodrun_tpu --host-discovery-script ./discover.sh --min-np 1 \\
        python examples/elastic_resnet.py
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import resnet_apply, resnet_init


def main():
    hvd.init()
    v = resnet_init(jax.random.PRNGKey(0), 18, num_classes=10)
    cfg = v["config"]
    opt = hvd.DistributedOptimizer(optax.sgd(0.01 * hvd.size(),
                                             momentum=0.9))

    state = hvd.elastic.TpuState(
        params={"params": v["params"], "batch_stats": v["batch_stats"]},
        opt_state=opt.init(v["params"]),
        epoch=0, batch_idx=0)

    x = jnp.asarray(np.random.rand(
        16 * hvd.local_size(), 32, 32, 3).astype(np.float32))
    y = jnp.asarray(np.random.randint(0, 10, size=16 * hvd.local_size()))

    @hvd.data_parallel
    def train_step(model, opt_state, batch):
        xb, yb = batch

        def loss_fn(p):
            logits, ns = resnet_apply(
                {"params": p, "batch_stats": model["batch_stats"],
                 "config": cfg},
                xb, train=True, axis_name=hvd.GLOBAL_AXIS)
            onehot = jax.nn.one_hot(yb, 10)
            return -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * onehot, -1)), ns

        (loss, ns), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(model["params"])
        updates, opt_state2 = opt.update(grads, opt_state, model["params"])
        params = optax.apply_updates(model["params"], updates)
        return {"params": params, "batch_stats": ns}, opt_state2, loss

    @hvd.elastic.run
    def train(state):
        batches_per_epoch = 8
        while state.epoch < 4:
            while state.batch_idx < batches_per_epoch:
                batch = hvd.shard_batch((x, y))
                state.params, state.opt_state, loss = train_step(
                    state.params, state.opt_state, batch)
                state.batch_idx += 1
                if state.batch_idx % 4 == 0:
                    state.commit()   # snapshot + host-update check
            if hvd.rank() == 0:
                print(f"epoch {state.epoch}: loss={float(loss):.4f} "
                      f"size={hvd.size()}", flush=True)
            state.epoch += 1
            state.batch_idx = 0
            state.commit()

    train(state)
    return 0


if __name__ == "__main__":
    sys.exit(main())
