"""Multi-slice (hierarchical) data parallelism example.

Reference: NCCLHierarchicalAllreduce (ops/nccl_operations.cc) — the
two-tier reduce for two-tier networks.  On a TPU multipod: `dcn` slices
over the data-center network, chips within a slice over ICI; gradients
reduce-scatter over ICI, allreduce over DCN on 1/ici_size of the bytes,
then all-gather over ICI.

Runs on the 8-device CPU sim (2 virtual slices x 4 chips):

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/hierarchical_multislice.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel import create_hierarchical_mesh
from horovod_tpu.parallel.hierarchical import hierarchical_allreduce

# jax < 0.5 only exposes jax.shard_map through the compat alias the
# horovod_tpu import installs — bind it after that import.
shard_map = jax.shard_map


def main():
    hvd.init()
    n = len(jax.devices())
    assert n >= 4 and n % 2 == 0, f"need >=4 even devices, have {n}"
    mesh = create_hierarchical_mesh(dcn=2, ici=n // 2)
    print(f"mesh: {dict(mesh.shape)}")

    params = {"w": jnp.zeros((4,)), "b": jnp.zeros(())}
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    bspec = P(("dcn", hvd.GLOBAL_AXIS))

    def step(params, opt_state, batch):
        x, y = batch

        def loss_fn(p):
            pred = x @ p["w"] + p["b"]
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # ICI reduce-scatter -> DCN allreduce -> ICI all-gather, fused
        # across the gradient tree.
        grads = hierarchical_allreduce(grads, "dcn")
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    sm = shard_map(step, mesh=mesh,
                   in_specs=(P(), P(), (bspec, bspec)),
                   out_specs=(P(), P(), P()), check_vma=False)
    compiled = jax.jit(sm)

    rng = np.random.RandomState(0)
    w_true = np.asarray([1.0, -2.0, 3.0, 0.5], np.float32)
    for i in range(30):
        x = rng.randn(n * 4, 4).astype(np.float32)
        y = x @ w_true + 0.7
        batch = jax.device_put((x, y), NamedSharding(mesh, bspec))
        params, opt_state, loss = compiled(params, opt_state, batch)
    print(f"final loss {float(loss):.5f}; "
          f"w={np.asarray(params['w']).round(2)} (true {w_true})")
    assert float(loss) < 0.05


if __name__ == "__main__":
    main()
