"""Serving benchmark: continuous vs static batching on a seeded trace.

decode_bench.py is the load generator for the serving stack
(horovod_tpu/serve): it replays the SAME seeded mixed-length request
trace against two InferenceServers that differ ONLY in admission
policy — ``fifo`` (continuous batching: admit/evict per decode step)
vs ``static`` (wave batching: the whole batch drains before the next
wave boards) — and reports p50/p99 request latency, tokens/sec/chip,
batch occupancy, and KV-pool utilization for each, plus the speedup.

Each config runs in a fresh killable subprocess (the wedged-tunnel
defense from flash_sweep.py) so a hang kills one child, not the sweep.
One JSON line per config on stdout, human table on stderr, and a
machine-readable record appended to BENCH_serve.json (stale-gated
comparison against the previous record, docs/SERVING.md).

Usage:  python decode_bench.py            # real chip
        JAX_PLATFORMS=cpu python decode_bench.py --tiny   # smoke
"""

import argparse
import json
import os
import subprocess
import sys

# (tag, cfg_kwargs, quantize, max_batch, n_requests)
CONFIGS = [
    ("mha",        {},                      None,   8, 48),
    ("gqa4",       {"n_kv_heads": 2},       None,   8, 48),
    ("gqa4+int8",  {"n_kv_heads": 2},       "int8", 8, 48),
    ("b16",        {"n_kv_heads": 2},       None,  16, 96),
]

CHILD_CODE = r"""
import json, sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp

if {tiny!r} == "1":
    jax.config.update("jax_platforms", "cpu")

from horovod_tpu.models import TransformerConfig, transformer_init
from horovod_tpu.serve import InferenceServer
from horovod_tpu.serve.loadgen import make_trace, run_trace

kw = json.loads(sys.argv[1])
quantize = sys.argv[4] or None
max_batch, n_requests = int(sys.argv[2]), int(sys.argv[3])
d_model = 128 if {tiny!r} == "1" else 1024
layers = 2 if {tiny!r} == "1" else 8
cfg = TransformerConfig(
    vocab_size=512 if {tiny!r} == "1" else 8192,
    d_model=d_model, n_heads=d_model // 32, d_head=32,
    d_ff=4 * d_model, n_layers=layers,
    compute_dtype=jnp.float32 if {tiny!r} == "1" else None, **kw)
params = transformer_init(jax.random.PRNGKey(0), cfg)

# The realistic serving mix: mostly short answers plus a ~25% tail of
# long generations (bimodal budgets).  That tail is what wave batching
# wastes on — one long request pins every row of its wave — and what
# continuous batching's per-step evictions reclaim.
if {tiny!r} == "1":
    prompt_lens, lo, hi, llo, lhi = (4, 8), 2, 8, 40, 56
    max_seq = 8 + 56
else:
    prompt_lens, lo, hi, llo, lhi = (64, 128, 256), 16, 64, 192, 256
    max_seq = 256 + 256
trace = make_trace(7, n_requests, cfg.vocab_size,
                   prompt_lens=prompt_lens, max_new_lo=lo,
                   max_new_hi=hi, long_frac=0.25, long_lo=llo,
                   long_hi=lhi, arrival_every=0.5)

out = {{}}
for policy in ("fifo", "static"):
    # Replay 1 + 3 times on fresh servers: the first run absorbs every
    # prefill/step compile (the jit cache is process-wide) so policy
    # order can't bias the A/B through compilation; of the three timed
    # replays the FASTEST is reported (standard best-of-N — scheduler
    # noise only ever slows a run down).
    best = None
    for rep in range(4):
        srv = InferenceServer(params, cfg, max_seq_tokens=max_seq,
                              max_batch=max_batch, quantize=quantize,
                              policy=policy, seed=0)
        stats = run_trace(srv, trace)
        if rep and (best is None or stats["wall_s"] < best["wall_s"]):
            best = stats
    out[policy] = best
    del out[policy]["slo_decisions"]
out["speedup_tokens_per_sec"] = (
    out["fifo"]["tokens_per_sec_per_chip"]
    / out["static"]["tokens_per_sec_per_chip"])
print(json.dumps(out))
"""


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tiny", action="store_true",
                   help="small config / CPU smoke")
    p.add_argument("--out", default="BENCH_serve.json",
                   help="machine-readable record file (JSON lines)")
    args = p.parse_args()
    repo = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, repo)
    from horovod_tpu.serve.loadgen import append_record, \
        read_latest_record
    prev = read_latest_record(os.path.join(repo, args.out))
    code = CHILD_CODE.format(repo=repo, tiny="1" if args.tiny else "0")
    records = {}
    for tag, kw, quantize, max_batch, n_requests in CONFIGS:
        if args.tiny:
            max_batch, n_requests = min(max_batch, 8), 48
        try:
            r = subprocess.run(
                [sys.executable, "-c", code, json.dumps(kw),
                 str(max_batch), str(n_requests), quantize or ""],
                capture_output=True, text=True, timeout=1800)
        except subprocess.TimeoutExpired:
            print(json.dumps({"config": tag, "error": "timeout"}),
                  flush=True)
            continue
        if r.returncode != 0:
            print(json.dumps({"config": tag,
                              "error": f"exit {r.returncode}"}),
                  flush=True)
            print(f"{tag}: {r.stderr[-300:]}", file=sys.stderr,
                  flush=True)
            continue
        res = json.loads(r.stdout.strip().splitlines()[-1])
        records[tag] = res
        print(json.dumps({"config": tag, "max_batch": max_batch,
                          **res}), flush=True)
        f, s = res["fifo"], res["static"]
        print(f"{tag:10s} continuous {f['tokens_per_sec_per_chip']:9.0f}"
              f" tok/s/chip (occ {f['batch_occupancy_mean']:4.2f}, "
              f"p99 {f['request_p99_ms']:7.1f} ms)  static "
              f"{s['tokens_per_sec_per_chip']:9.0f} tok/s/chip (occ "
              f"{s['batch_occupancy_mean']:4.2f})  speedup "
              f"{res['speedup_tokens_per_sec']:5.2f}x",
              file=sys.stderr, flush=True)
        # TTFT / inter-token percentiles come from the serving
        # histograms (hvd_serve_ttft_seconds / _intertoken_seconds),
        # delta-snapshotted per replay by run_trace.
        print(f"{'':10s} ttft p50/p99 "
              f"{f.get('ttft_p50_ms', 0.0):7.1f}/"
              f"{f.get('ttft_p99_ms', 0.0):7.1f} ms   itl p50/p99 "
              f"{f.get('itl_p50_ms', 0.0):6.2f}/"
              f"{f.get('itl_p99_ms', 0.0):6.2f} ms",
              file=sys.stderr, flush=True)
    if records:
        rec = {"bench": "decode_bench", "kind": "continuous_vs_static",
               "tiny": bool(args.tiny), "configs": records}
        if prev is not None and prev.get("bench") == "decode_bench" \
                and not prev.get("stale"):
            rec["vs_prev"] = {
                t: records[t]["fifo"]["tokens_per_sec_per_chip"]
                / prev["configs"][t]["fifo"]["tokens_per_sec_per_chip"]
                for t in records
                if t in prev.get("configs", {})}
        append_record(os.path.join(repo, args.out), rec)


if __name__ == "__main__":
    main()
