"""KV-cache decode benchmark: prefill and per-token decode throughput.

Measures the inference path (models/decode.py) the way bench.py
measures training: wall-clock per compiled step, warmup discarded,
JSON line per config on stdout, human table on stderr.  Configs cover
the levers that matter at decode: GQA (cache bytes / group), sliding
window (band-masked ring), and batch.

Each config runs in a fresh killable subprocess (the wedged-tunnel
defense from flash_sweep.py) so a hang kills one child, not the sweep.

Usage:  python decode_bench.py            # real chip
        JAX_PLATFORMS=cpu python decode_bench.py --tiny   # smoke
"""

import argparse
import json
import os
import subprocess
import sys

# (tag, cfg_kwargs, quantize, batch, prompt_len, new_tokens)
CONFIGS = [
    ("mha",        {},                      None,   8, 512, 64),
    ("gqa4",       {"n_kv_heads": 2},       None,   8, 512, 64),
    ("mqa",        {"n_kv_heads": 1},       None,   8, 512, 64),
    # window < T0 so the band genuinely truncates during prefill AND
    # decode (a window larger than the whole run never masks anything
    # and used to trip the cache-capacity guard — r4 advisor finding).
    ("gqa+win256", {"n_kv_heads": 2,
                    "attn_window": 256},    None,   8, 512, 64),
    ("gqa4+int8",  {"n_kv_heads": 2},       "int8", 8, 512, 64),
]

CHILD_CODE = r"""
import json, sys, time
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp

if {tiny!r} == "1":
    jax.config.update("jax_platforms", "cpu")

from horovod_tpu.models import (
    TransformerConfig, transformer_init, transformer_prefill,
    transformer_decode_step, init_decode_cache)

kw = json.loads(sys.argv[1])
quantize = sys.argv[5] or None
B, T0, N = (int(a) for a in sys.argv[2:5])
d_model = 256 if {tiny!r} == "1" else 1024
layers = 2 if {tiny!r} == "1" else 8
cfg = TransformerConfig(
    vocab_size=8192, d_model=d_model, n_heads=d_model // 64, d_head=64,
    d_ff=4 * d_model, n_layers=layers, **kw)
params = transformer_init(jax.random.PRNGKey(0), cfg)
prompt = jax.random.randint(jax.random.PRNGKey(1), (B, T0), 0,
                            cfg.vocab_size)

cache = init_decode_cache(cfg, B, T0 + N + 4,  # + warmup steps
                          quantize=quantize)
pf = jax.jit(lambda c, p: transformer_prefill(params, c, p, cfg))
step = jax.jit(lambda c, t: transformer_decode_step(params, c, t, cfg))

# prefill timing (compile excluded via a throwaway warmup)
lg, warm = pf(init_decode_cache(cfg, B, T0 + N + 4,
                                quantize=quantize), prompt)
jax.block_until_ready(lg)
t0 = time.perf_counter()
lg, cache = pf(cache, prompt)
jax.block_until_ready(lg)
t_prefill = time.perf_counter() - t0

# decode timing: warmup 4 steps, time N
tok = jnp.argmax(lg, axis=-1)
for _ in range(4):
    lg, cache = step(cache, tok)
    tok = jnp.argmax(lg, axis=-1)
jax.block_until_ready(lg)
t0 = time.perf_counter()
for _ in range(N):
    lg, cache = step(cache, tok)
    tok = jnp.argmax(lg, axis=-1)
jax.block_until_ready(lg)
dt = time.perf_counter() - t0
kv_mb = sum(a.size * a.dtype.itemsize for a in
            jax.tree_util.tree_leaves((cache["k"], cache["v"]))) / 1e6
print(json.dumps({{
    "prefill_ms": t_prefill * 1e3,
    "prefill_tok_s": B * T0 / t_prefill,
    "decode_ms_tok": dt / N * 1e3,
    "decode_tok_s": B * N / dt,
    "kv_cache_mb": kv_mb,
}}))
"""


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tiny", action="store_true",
                   help="small config / CPU smoke")
    args = p.parse_args()
    repo = os.path.dirname(os.path.abspath(__file__))
    code = CHILD_CODE.format(repo=repo, tiny="1" if args.tiny else "0")
    for tag, kw, quantize, B, T0, N in CONFIGS:
        if args.tiny:
            B, T0, N = 2, 64, 8
            if kw.get("attn_window"):
                kw = dict(kw, attn_window=32)
        try:
            r = subprocess.run(
                [sys.executable, "-c", code, json.dumps(kw),
                 str(B), str(T0), str(N), quantize or ""],
                capture_output=True, text=True, timeout=900)
        except subprocess.TimeoutExpired:
            print(json.dumps({"config": tag, "error": "timeout"}),
                  flush=True)
            continue
        if r.returncode != 0:
            print(json.dumps({"config": tag,
                              "error": f"exit {r.returncode}"}),
                  flush=True)
            print(f"{tag}: {r.stderr[-300:]}", file=sys.stderr,
                  flush=True)
            continue
        res = json.loads(r.stdout.strip().splitlines()[-1])
        print(json.dumps({"config": tag, "B": B, "T0": T0, **res}),
              flush=True)
        print(f"{tag:10s} prefill {res['prefill_ms']:8.1f} ms "
              f"({res['prefill_tok_s']:9.0f} tok/s)  decode "
              f"{res['decode_ms_tok']:6.2f} ms/tok "
              f"({res['decode_tok_s']:7.0f} tok/s)  kv "
              f"{res['kv_cache_mb']:7.1f} MB",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
