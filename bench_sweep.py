"""Single-chip MFU sweep: batch size × conv0 space-to-depth × input
dtype × XLA scheduler flags, on the ResNet-50 headline config.

Run on a healthy accelerator (`python bench_sweep.py`); each
configuration executes in a fresh killable subprocess (the wedged-tunnel
defense from bench.py) and reports img/s/chip.  Results feed
docs/PERF_NOTES.md and pick the defaults bench.py ships with
(r03 verdict task 3: the named levers are input layout at 224px and the
host→HBM pipeline; conv0 space-to-depth is the layout lever).

Output: one JSON line per config on stdout; human table on stderr.
"""

import itertools
import json
import os
import subprocess
import sys

CONFIGS = []
for batch, s2d in itertools.product((128, 256, 512), (0, 1)):
    CONFIGS.append({"batch": batch, "s2d": s2d, "flags": ""})
# XLA latency-hiding scheduler sweep on the best-known batch.
for flags in (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
):
    CONFIGS.append({"batch": 256, "s2d": 1, "flags": flags})

CHILD_CODE = r"""
import json, sys, time
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp, optax
import horovod_tpu as hvd
from horovod_tpu.models import resnet_init
from bench import build_step, time_steps

hvd.init()
batch = int(sys.argv[1])
image = 224
rng = jax.random.PRNGKey(42)
v = resnet_init(rng, 50, num_classes=1000)
opt = optax.sgd(0.0125, momentum=0.9)
x = jax.random.normal(jax.random.PRNGKey(0), (batch, image, image, 3),
                      jnp.bfloat16).astype(jnp.float32)
y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, 1000)
state = {{"params": v["params"], "batch_stats": v["batch_stats"]}}
opt_state = opt.init(state["params"])
step = hvd.data_parallel(build_step(opt, v["config"], distributed=True))
sb = hvd.shard_batch((x, y))
t, _, _ = time_steps(step, state, opt_state, sb, warmup=5, iters=20)
print(json.dumps({{"img_sec_per_chip": batch / t / hvd.size(),
                   "ms_step": t * 1e3}}))
"""


def main():
    repo = os.path.dirname(os.path.abspath(__file__))
    code = CHILD_CODE.format(repo=repo)
    results = []
    for cfg in CONFIGS:
        env = dict(os.environ)
        env["HOROVOD_CONV0_SPACE_TO_DEPTH"] = str(cfg["s2d"])
        if cfg["flags"]:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "") + " " + cfg["flags"]).strip()
        try:
            r = subprocess.run(
                [sys.executable, "-c", code, str(cfg["batch"])],
                capture_output=True, text=True, timeout=600, env=env)
        except subprocess.TimeoutExpired:
            print(f"timeout: {cfg}", file=sys.stderr, flush=True)
            continue
        if r.returncode != 0:
            print(f"failed: {cfg}: {r.stderr[-300:]}",
                  file=sys.stderr, flush=True)
            continue
        out = json.loads(r.stdout.strip().splitlines()[-1])
        row = {**cfg, **out}
        results.append(row)
        print(json.dumps(row), flush=True)
        print(f"batch={cfg['batch']} s2d={cfg['s2d']} "
              f"flags='{cfg['flags']}' -> "
              f"{out['img_sec_per_chip']:.1f} img/s/chip "
              f"({out['ms_step']:.1f} ms)", file=sys.stderr, flush=True)
    if results:
        best = max(results, key=lambda r: r["img_sec_per_chip"])
        print(f"best: {best}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
