"""Flash-attention block-size sweep: pick HOROVOD_FLASH_BLOCK_Q/K.

The r04 kernel rework runs the score/output/gradient matmuls in the
input dtype (bf16 on the MXU) and makes the q/k block sizes
env-tunable; this sweep measures fwd+bwd wall time across (T, bq, bk)
combinations on the real chip to pick shipping defaults and quantify
the mixed-precision win vs the r04 long-T sweep (flash_r4.jsonl, which
ran the all-f32 kernel at 128x128).

Each config runs in a fresh killable subprocess (same wedge defense as
flash_sweep.py).  One JSON line per config on stdout; human summary on
stderr.  Results feed docs/PERF_NOTES.md.
"""

import json
import os
import subprocess
import sys

# (T, B) x (bq, bk).  T=4096/8192 is the regime where the f32 kernel
# lost to XLA dense (0.89-0.95x); T=16384 is the only-flash regime.
CONFIGS = [(4096, 2), (8192, 1), (16384, 1)]
BLOCKS = [(128, 128), (256, 256), (512, 512), (256, 512),
          (512, 256), (128, 512), (1024, 512)]

CHILD_CODE = r"""
import json, sys, time
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp

import os
T, B, BQ, BK = (int(a) for a in sys.argv[1:5])
# The kernel reads tile sizes from env; set them from argv so a
# hand-rerun of this child command reproduces the same sweep point.
os.environ["HOROVOD_FLASH_BLOCK_Q"] = str(BQ)
os.environ["HOROVOD_FLASH_BLOCK_K"] = str(BK)
H, D = 8, 64
q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, T, H, D),
                             jnp.bfloat16) for i in range(3))

from horovod_tpu.ops.flash_attention import flash_attention as attn


def loss(q, k, v):
    return jnp.sum(attn(q, k, v, causal=True).astype(jnp.float32))


step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))


def sync(x):
    import numpy as np
    jax.block_until_ready(x)
    return float(np.asarray(jax.tree_util.tree_leaves(x)[0]).ravel()[0])


warmup, iters = 2, 5
for _ in range(warmup):
    g = step(q, k, v)
sync(g)
t0 = time.perf_counter()
for _ in range(iters):
    g = step(q, k, v)
sync(g)
dt = (time.perf_counter() - t0) / iters
print(json.dumps({{"ms_iter": dt * 1e3, "tok_per_s": B * T / dt}}))
"""


def main():
    repo = os.path.dirname(os.path.abspath(__file__))
    code = CHILD_CODE.format(repo=repo)
    best = {}
    for T, B in CONFIGS:
        for bq, bk in BLOCKS:
            if T % bq or T % bk:
                continue
            env = dict(os.environ)
            env.pop("HOROVOD_FLASH_ATTENTION", None)
            env["HOROVOD_FLASH_BLOCK_Q"] = str(bq)
            env["HOROVOD_FLASH_BLOCK_K"] = str(bk)
            tag = f"T={T} bq={bq} bk={bk}"
            try:
                r = subprocess.run(
                    [sys.executable, "-c", code,
                     str(T), str(B), str(bq), str(bk)],
                    capture_output=True, text=True, timeout=900, env=env)
            except subprocess.TimeoutExpired:
                print(f"timeout: {tag}", file=sys.stderr, flush=True)
                print(json.dumps({"T": T, "B": B, "bq": bq, "bk": bk,
                                  "error": "timeout"}), flush=True)
                continue
            if r.returncode != 0:
                kind = ("oom" if "RESOURCE_EXHAUSTED" in r.stderr
                        else "error")
                print(f"{kind}: {tag}: {r.stderr[-300:]}",
                      file=sys.stderr, flush=True)
                print(json.dumps({"T": T, "B": B, "bq": bq, "bk": bk,
                                  "error": kind}), flush=True)
                continue
            res = json.loads(r.stdout.strip().splitlines()[-1])
            print(json.dumps({"T": T, "B": B, "bq": bq, "bk": bk, **res}),
                  flush=True)
            print(f"{tag}: {res['ms_iter']:.1f} ms/iter",
                  file=sys.stderr, flush=True)
            cur = best.get(T)
            if cur is None or res["ms_iter"] < cur[2]:
                best[T] = (bq, bk, res["ms_iter"])
    for T, (bq, bk, ms) in sorted(best.items()):
        print(f"best T={T}: bq={bq} bk={bk} at {ms:.1f} ms",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
