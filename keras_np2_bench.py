"""Multi-rank Keras bridge cost: np=2 DistributedOptimizer training vs
plain Keras on the same host.

The in-process `keras_vs_baseline` in bench.py measures the np=1 path,
where the size-1 short-circuit makes the bridge free by construction.
This script measures the path that actually pays the bridge: a REAL
2-process `horovodrun_tpu` launch (each worker one CPU device), Keras
model compiled with hvd DistributedOptimizer, per-worker img/s compared
against single-process plain Keras on the identical model/batch — the
honest multi-rank overhead number for docs/PERF_NOTES.md (reference:
pytorch_synthetic_benchmark.py's per-rank reporting discipline).

Usage: python keras_np2_bench.py   (host-only; does not touch the TPU)
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))

WORKER = r"""
import json, os, sys, time
import numpy as np
import tensorflow as tf

tf.random.set_seed(0)
np.random.seed(0)
model_kind = os.environ.get("KB_MODEL", "mnist")
if model_kind not in ("mnist", "big"):
    raise SystemExit(f"KB_MODEL must be 'mnist' or 'big', got {model_kind!r}")
if model_kind == "mnist":
    # 25k params, ~0.17 ms/img steps: the fixed per-step bridge cost
    # DOMINATES by construction — the lower-bound retention case.
    batch = 64
    x = np.random.randn(batch, 28, 28, 1).astype("float32")
    y = np.random.randint(0, 10, (batch,))
    model = tf.keras.Sequential([
        tf.keras.layers.Input((28, 28, 1)),
        tf.keras.layers.Conv2D(16, 3, activation="relu"),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Conv2D(32, 3, activation="relu"),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10),
    ])
    warmup, iters = 3, 12
else:
    # ~7M-param convnet on 32x32x3 with a wide dense head: step times
    # in the hundreds of ms, i.e. a realistic compute:bridge ratio —
    # the retention number real models see.
    batch = 64
    x = np.random.randn(batch, 32, 32, 3).astype("float32")
    y = np.random.randint(0, 10, (batch,))
    model = tf.keras.Sequential([
        tf.keras.layers.Input((32, 32, 3)),
        tf.keras.layers.Conv2D(64, 3, padding="same", activation="relu"),
        tf.keras.layers.Conv2D(64, 3, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Conv2D(128, 3, padding="same", activation="relu"),
        tf.keras.layers.Conv2D(128, 3, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(768, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    warmup, iters = 2, 6
loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)

mode = sys.argv[1]
if mode == "dist":
    import horovod_tpu.tensorflow.keras as hvd_k
    import horovod_tpu as hvd
    hvd.init()
    opt = hvd_k.DistributedOptimizer(tf.keras.optimizers.SGD(0.01))
else:
    opt = tf.keras.optimizers.SGD(0.01)
model.compile(optimizer=opt, loss=loss_fn)

for _ in range(warmup):
    model.train_on_batch(x, y)
t0 = time.perf_counter()
for _ in range(iters):
    model.train_on_batch(x, y)
img_sec = batch * iters / (time.perf_counter() - t0)
out = os.environ.get("KB_OUT")
rank = int(os.environ.get("HOROVOD_RANK", 0))
with open(os.path.join(out, f"{mode}_rank{rank}.json"), "w") as f:
    json.dump({"img_sec": img_sec}, f)
"""


def main():
    import tempfile

    out = tempfile.mkdtemp(prefix="keras_np2_")
    wpath = os.path.join(out, "worker.py")
    with open(wpath, "w") as f:
        f.write(WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["KB_OUT"] = out
    env.pop("XLA_FLAGS", None)

    # Denominator: TWO CONCURRENT plain-Keras processes (no horovod).
    # A single plain process would own every host core, so comparing it
    # against two co-located workers would charge CPU-sharing to the
    # bridge; two independent processes pay the same core split and
    # isolate the actual collective/bridge cost.
    procs = []
    try:
        for i in (0, 1):
            e = dict(env)
            e["HOROVOD_RANK"] = str(i)
            procs.append(subprocess.Popen(
                [sys.executable, wpath, "plain"],
                stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, env=e))
        for p in procs:
            _, err = p.communicate(timeout=600)
            if p.returncode != 0:
                print(f"plain run failed: {err.decode()[-500:]}",
                      file=sys.stderr)
                return 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    plains = [json.load(open(os.path.join(out, f"plain_rank{i}.json")))
              ["img_sec"] for i in (0, 1)]
    plain = sum(plains) / len(plains)

    # np=2 distributed.
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, wpath, "dist"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    if r.returncode != 0:
        print(f"np=2 run failed:\n{r.stdout[-800:]}\n{r.stderr[-800:]}",
              file=sys.stderr)
        return 1
    ranks = []
    for rank in (0, 1):
        p = os.path.join(out, f"dist_rank{rank}.json")
        ranks.append(json.load(open(p))["img_sec"])
    per_worker = sum(ranks) / len(ranks)
    print(json.dumps({
        "model": os.environ.get("KB_MODEL", "mnist"),
        "plain_img_sec_per_worker_concurrent": round(plain, 1),
        "np2_img_sec_per_worker": round(per_worker, 1),
        "np2_img_sec_ranks": [round(v, 1) for v in ranks],
        "np2_total_img_sec": round(sum(ranks), 1),
        "bridge_retention": round(per_worker / plain, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
