"""Speculative vs plain decode throughput (models/decode.py r5).

Measures tokens/s of target-only greedy decode against speculative
decoding (draft-propose / target-verify) on the same target model.
Like decode_bench.py, each config runs in a fresh killable subprocess
(wedged-tunnel defense); one JSON line per config on stdout.

The interesting regime is a target whose per-token step is dispatch- or
HBM-bound and a draft ~10x smaller: each round replaces gamma+1 target
steps with one chunked target forward + one target step.  With random
(untrained) weights the draft disagrees almost always, so the measured
speedup here is a LOWER bound — acceptance on real checkpoints is what
makes gamma pay; the bench also reports accept_rate so the arithmetic
(tokens per target dispatch = 1 + accept_rate * gamma) is visible.
A self-speculation config (draft == target) shows the 100%-acceptance
upper bound on round efficiency with this implementation's overheads.

Usage:  python spec_bench.py            # real chip
        JAX_PLATFORMS=cpu python spec_bench.py --tiny   # smoke
"""

import argparse
import json
import os
import subprocess
import sys

# (tag, target_d, target_L, draft_d, draft_L, gamma, prompt, new)
CONFIGS = [
    ("plain",      1024, 8, 0,   0, 0, 512, 128),
    ("spec_g4",    1024, 8, 256, 2, 4, 512, 128),
    ("spec_g8",    1024, 8, 256, 2, 8, 512, 128),
    ("self_g4",    1024, 8, -1, -1, 4, 512, 128),
]

CHILD_CODE = r"""
import json, sys, time
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp

if {tiny!r} == "1":
    jax.config.update("jax_platforms", "cpu")

from horovod_tpu.models import (
    TransformerConfig, transformer_init, transformer_generate,
    transformer_speculative_generate)

td, tl, dd, dl, gamma, T0, N = (int(a) for a in sys.argv[1:8])
V = 8192

def cfg_for(d, L):
    return TransformerConfig(
        vocab_size=V, d_model=d, n_heads=max(1, d // 64),
        d_head=min(64, d), d_ff=4 * d, n_layers=L)

cfg = cfg_for(td, tl)
params = transformer_init(jax.random.PRNGKey(0), cfg)
prompt = jax.random.randint(jax.random.PRNGKey(1), (1, T0), 0, V)

if gamma == 0:
    # Warmup at the SAME shapes as the timed run (scan length and cache
    # capacity key the compiled programs; a short warmup would leave
    # the timed region paying the compile).
    transformer_generate(params, cfg, prompt, N)
    t0 = time.perf_counter()
    toks, _ = transformer_generate(params, cfg, prompt, N)
    jax.block_until_ready(toks)
    dt = time.perf_counter() - t0
    print(json.dumps({{"tok_s": N / dt, "ms_tok": dt / N * 1e3}}))
else:
    if dd < 0:
        dcfg, dparams = cfg, params        # self-speculation
    else:
        dcfg = cfg_for(dd, dl)
        dparams = transformer_init(jax.random.PRNGKey(7), dcfg)
    # Warmup with the timed run's N so cache capacity (and thus every
    # jitted program shape) matches the timed call exactly.
    transformer_speculative_generate(
        params, cfg, dparams, dcfg, prompt, N, gamma=gamma)
    t0 = time.perf_counter()
    toks, stats = transformer_speculative_generate(
        params, cfg, dparams, dcfg, prompt, N, gamma=gamma)
    jax.block_until_ready(toks)
    dt = time.perf_counter() - t0
    print(json.dumps({{"tok_s": N / dt, "ms_tok": dt / N * 1e3,
                      "accept_rate": stats["accept_rate"],
                      "rounds": stats["rounds"]}}))
"""


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tiny", action="store_true")
    args = p.parse_args()
    repo = os.path.dirname(os.path.abspath(__file__))
    code = CHILD_CODE.format(repo=repo, tiny="1" if args.tiny else "0")
    for tag, td, tl, dd, dl, gamma, T0, N in CONFIGS:
        if args.tiny:
            td, tl = 128, 2
            dd, dl = (dd if dd < 0 else 64), (dl if dd < 0 else 1)
            T0, N = 32, 16
        try:
            r = subprocess.run(
                [sys.executable, "-c", code] +
                [str(a) for a in (td, tl, dd, dl, gamma, T0, N)],
                capture_output=True, text=True, timeout=1800)
        except subprocess.TimeoutExpired:
            print(json.dumps({"config": tag, "error": "timeout"}),
                  flush=True)
            continue
        if r.returncode != 0:
            print(json.dumps({"config": tag,
                              "error": f"exit {r.returncode}"}),
                  flush=True)
            print(f"{tag}: {r.stderr[-300:]}", file=sys.stderr, flush=True)
            continue
        res = json.loads(r.stdout.strip().splitlines()[-1])
        print(json.dumps({"config": tag, **res}), flush=True)
        extra = (f"  accept {res['accept_rate']:.2f} over "
                 f"{res['rounds']} rounds" if "accept_rate" in res else "")
        print(f"{tag:9s} {res['tok_s']:8.1f} tok/s "
              f"({res['ms_tok']:6.2f} ms/tok){extra}",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
