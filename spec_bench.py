"""Serving benchmark: speculative decoding under the SLO controller.

spec_bench.py is the second serving load generator (see
decode_bench.py for the continuous-vs-static A/B): it replays a seeded
trace against the continuous-batching InferenceServer three ways —
plain decode, forced speculative rounds (draft-propose / chunked
verify inside the serving loop), and SLO-toggled speculation
(HOROVOD_SERVE_SLO_MS semantics: spec flips on when observed per-token
p99 exceeds the target) — and reports p50/p99 latency, tokens/sec/chip
and, for the toggled run, the controller's decision trace.

With random weights an independent draft rarely agrees with the
target, so forced-spec numbers here are a LOWER bound; the self-draft
config shows the 100%-acceptance upper bound on round efficiency.
Each config runs in a fresh killable subprocess; one JSON line per
config on stdout, human table on stderr, machine-readable record
appended to BENCH_serve.json.

Usage:  python spec_bench.py            # real chip
        JAX_PLATFORMS=cpu python spec_bench.py --tiny   # smoke
"""

import argparse
import json
import os
import subprocess
import sys

# (tag, mode, draft, gamma, max_batch, n_requests)
#   mode: plain | spec (forced) | slo (controller-toggled)
#   draft: none | small | self
CONFIGS = [
    ("plain",    "plain", "none",  0, 8, 32),
    ("spec_g4",  "spec",  "small", 4, 8, 32),
    ("self_g4",  "spec",  "self",  4, 8, 32),
    ("slo_g4",   "slo",   "small", 4, 8, 32),
]

CHILD_CODE = r"""
import json, sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp

if {tiny!r} == "1":
    jax.config.update("jax_platforms", "cpu")

from horovod_tpu.models import TransformerConfig, transformer_init
from horovod_tpu.serve import InferenceServer
from horovod_tpu.serve.loadgen import make_trace, run_trace

mode, draft, gamma, max_batch, n_requests = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
    int(sys.argv[5]))
tiny = {tiny!r} == "1"
V = 512 if tiny else 8192

def cfg_for(d, L):
    return TransformerConfig(
        vocab_size=V, d_model=d, n_heads=max(1, d // 32), d_head=32,
        d_ff=4 * d, n_layers=L,
        compute_dtype=jnp.float32 if tiny else None)

cfg = cfg_for(64 if tiny else 1024, 2 if tiny else 8)
params = transformer_init(jax.random.PRNGKey(0), cfg)
dparams = dcfg = None
if draft == "self":
    dparams, dcfg = params, cfg
elif draft == "small":
    dcfg = cfg_for(32 if tiny else 256, 1 if tiny else 2)
    dparams = transformer_init(jax.random.PRNGKey(7), dcfg)

if tiny:
    prompt_lens, lo, hi, max_seq = (4, 8), 4, 16, 8 + 16
else:
    prompt_lens, lo, hi, max_seq = (64, 128), 32, 128, 128 + 128
trace = make_trace(11, n_requests, V, prompt_lens=prompt_lens,
                   max_new_lo=lo, max_new_hi=hi, arrival_every=1.0)

# SLO for the toggled run: half the plain per-token p50, so the
# controller genuinely engages speculation mid-run.
slo_ms = None
if mode == "slo":
    probe = InferenceServer(params, cfg, max_seq_tokens=max_seq,
                            max_batch=max_batch)
    probe_stats = run_trace(probe, trace)
    slo_ms = probe_stats["token_p50_ms"] * 0.5

srv = InferenceServer(
    params, cfg, max_seq_tokens=max_seq, max_batch=max_batch,
    draft_params=dparams, draft_cfg=dcfg,
    gamma=gamma if gamma else None, slo_ms=slo_ms,
    force_spec=(mode == "spec"))
stats = run_trace(srv, trace)
stats["spec_rounds"] = srv.spec_steps
if mode != "slo":
    del stats["slo_decisions"]
print(json.dumps(stats))
"""


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--out", default="BENCH_serve.json",
                   help="machine-readable record file (JSON lines)")
    args = p.parse_args()
    repo = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, repo)
    from horovod_tpu.serve.loadgen import append_record
    code = CHILD_CODE.format(repo=repo, tiny="1" if args.tiny else "0")
    records = {}
    for tag, mode, draft, gamma, max_batch, n_requests in CONFIGS:
        if args.tiny:
            max_batch, n_requests = 4, 10
        try:
            r = subprocess.run(
                [sys.executable, "-c", code, mode, draft, str(gamma),
                 str(max_batch), str(n_requests)],
                capture_output=True, text=True, timeout=1800)
        except subprocess.TimeoutExpired:
            print(json.dumps({"config": tag, "error": "timeout"}),
                  flush=True)
            continue
        if r.returncode != 0:
            print(json.dumps({"config": tag,
                              "error": f"exit {r.returncode}"}),
                  flush=True)
            print(f"{tag}: {r.stderr[-300:]}", file=sys.stderr,
                  flush=True)
            continue
        res = json.loads(r.stdout.strip().splitlines()[-1])
        records[tag] = res
        print(json.dumps({"config": tag, **res}), flush=True)
        extra = f"  spec rounds {res['spec_rounds']}" \
            if res.get("spec_rounds") else ""
        if "slo_decisions" in res:
            extra += f"  slo flips {len(res['slo_decisions'])}"
        print(f"{tag:9s} {res['tokens_per_sec_per_chip']:9.0f} "
              f"tok/s/chip  tok p99 {res['token_p99_ms']:7.2f} ms  "
              f"req p99 {res['request_p99_ms']:8.1f} ms  "
              f"ttft p50/p99 {res.get('ttft_p50_ms', 0.0):6.1f}/"
              f"{res.get('ttft_p99_ms', 0.0):6.1f} ms  "
              f"itl p50/p99 {res.get('itl_p50_ms', 0.0):5.2f}/"
              f"{res.get('itl_p99_ms', 0.0):5.2f} ms{extra}",
              file=sys.stderr, flush=True)
    if records:
        append_record(os.path.join(repo, args.out),
                      {"bench": "spec_bench", "kind": "slo_speculative",
                       "tiny": bool(args.tiny), "configs": records})


if __name__ == "__main__":
    main()
