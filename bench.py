"""Headline benchmark: ResNet-50 synthetic data, img/sec per chip.

Mirrors the reference's `examples/pytorch/pytorch_synthetic_benchmark.py`
(SURVEY.md §6, BASELINE.json metric "ResNet-50 img/sec/chip"): synthetic
images, SGD-momentum, train-mode batch norm, warmup then timed iterations.

TPU-first differences from the reference harness:
  - one compiled SPMD step (gradient allreduce fused into the step program)
    instead of eager grad hooks + background negotiation;
  - bf16 compute / f32 params;
  - input donation so weights update in place in HBM.

Resilience contract: the accelerator backend can *error* or *hang* during
setup (both observed).  The main process therefore (1) probes the backend in
a killable subprocess with timeout+retry before touching it, (2) falls back
to the CPU host platform when the accelerator is unreachable, and (3) always
exits through exactly ONE JSON line on stdout, even on failure.  All
diagnostics go to stderr.

Reported fields:
  value        — img/sec/chip of the framework's distributed step
  vs_baseline  — framework vs raw-JAX on identical work (1.0 = zero
                 framework overhead on one chip; >1.0 = fusion wins)
  scaling_eff_sim8 — simulated 8-device scaling efficiency: per-chip
                 throughput at n=8 over n=1 on the CPU host mesh (stand-in
                 for the >=90% pod-scale north star, BASELINE.md).
                 Trimmed median of >=7 paired runs with eff>1.0 pairs
                 rejected; spread and a bootstrap CI ship alongside.
  provenance   — "live" when the headline number was measured in this
                 run; "cached" when the accelerator was unreachable for
                 the whole probe window and the record carries the
                 last-known-good ON-CHIP measurement from
                 BENCH_CACHE.json (with its capture timestamp and
                 staleness) instead of silently degrading to a CPU
                 number.  A wedged chip degrades the record's
                 freshness, not its existence.
"""

import functools
import json
import os
import subprocess
import sys
import tempfile
import time

PROBE_TIMEOUT = float(os.environ.get("HOROVOD_BACKEND_PROBE_TIMEOUT", "120"))
PROBE_RETRIES = 2
# Extra patience for a *wedged* (hanging) accelerator: observed to
# recover on its own; keep probing this long before surrendering.  The
# surrender path now emits the cached last-known-good on-chip record,
# so the window is patience, not the difference between having a TPU
# record and not.  Worst-case unattended budget: 15 min probe + ~5 min
# CPU fallback bench + ~15 min 7-pair sim scaling ≈ 35 min (r03
# verdict task 1 explicitly asked for the window NOT to shrink;
# override via HOROVOD_BENCH_PROBE_WINDOW if a runner needs a tighter
# bound).
PROBE_WINDOW = float(os.environ.get("HOROVOD_BENCH_PROBE_WINDOW", "900"))

# Freshness window for reusing the cached on-chip record when the
# accelerator is unreachable: within it the reuse is a quiet note;
# beyond it the record is marked stale=True with a loud warning
# (instead of the old unconditional "(28.7 h old)" banner on every
# run silently reusing an arbitrarily old record).
CACHE_MAX_AGE_H = float(
    os.environ.get("HOROVOD_BENCH_CACHE_MAX_AGE_H", "24"))

# Last-known-good ON-CHIP results, refreshed every time the bench runs
# live on the accelerator.  Committed so a wedged-chip round still
# carries an on-chip record (provenance-marked).
CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BENCH_CACHE.json")


def load_cache():
    try:
        with open(CACHE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def store_cache(result: dict) -> None:
    """Persist a live on-chip result as the new last-known-good."""
    entry = dict(result)
    entry["captured_utc"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    entry["captured_unix"] = int(time.time())
    try:
        with open(CACHE_PATH, "w") as f:
            json.dump(entry, f, indent=1)
            f.write("\n")
    except OSError as e:
        log(f"could not persist bench cache: {e}")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(obj):
    print(json.dumps(obj), flush=True)


# ---------------------------------------------------------------------------
# Backend probe (subprocess so a wedged PJRT plugin can be killed)
# ---------------------------------------------------------------------------

def probe_accelerator() -> str:
    """Return the usable platform: 'tpu' if the accelerator initializes
    within the probe window, else 'cpu'.

    Hang-resilient: each probe runs in a killable subprocess; a hanging
    (wedged-tunnel) backend keeps being re-probed for up to
    PROBE_WINDOW seconds, since the wedge has been observed to clear on
    its own."""
    code = "import jax; print(jax.devices()[0].platform)"
    deadline = time.monotonic() + PROBE_WINDOW
    attempt = 0
    errors = 0
    while True:
        attempt += 1
        hung = False
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT)
            if r.returncode == 0:
                plat = r.stdout.strip().splitlines()[-1]
                log(f"probe attempt {attempt}: platform={plat}")
                if plat == "tpu":
                    return "tpu"
                return "cpu"
            log(f"probe attempt {attempt}: rc={r.returncode} "
                f"stderr tail: {r.stderr[-500:]}")
        except subprocess.TimeoutExpired:
            hung = True
            log(f"probe attempt {attempt}: backend init hung "
                f">{PROBE_TIMEOUT}s, killed")
        # Fast errors exhaust PROBE_RETRIES (counted separately from
        # hangs); hangs keep retrying until the window closes.
        if not hung:
            errors += 1
            if errors >= PROBE_RETRIES:
                break
        if time.monotonic() + PROBE_TIMEOUT > deadline:
            break
        time.sleep(15 if hung else 2)
    log("accelerator unreachable; falling back to CPU host platform")
    return "cpu"


# ---------------------------------------------------------------------------
# The measured step (shared by main bench and the sim-scaling child)
# ---------------------------------------------------------------------------

def build_step(opt, cfg, distributed: bool,
               reduce_grads_in_step: bool = True):
    """The measured train step.  `reduce_grads_in_step=False` leaves the
    gradient allreduce to `opt` itself (hvd.DistributedOptimizer with
    fused_apply: per-bucket reduce + apply chains instead of an
    allreduce barrier before one global update — the overlap-aware
    pipeline, the sim-scaling default)."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models import resnet_apply
    import horovod_tpu as hvd

    def step(state, opt_state, batch):
        x, y = batch

        def loss_fn(p):
            # Batch-norm stats are LOCAL per worker — reference parity:
            # Horovod's benchmark models use plain BatchNorm; cross-rank
            # SyncBatchNormalization is opt-in (sync_batch_norm.py).
            # Syncing here costs ~2 tiny collectives per BN layer per
            # pass and is what sank scaling_eff_sim8 to 0.85 in r02 (see
            # docs/PERF_NOTES.md).
            logits, ns = resnet_apply(
                {"params": p, "batch_stats": state["batch_stats"],
                 "config": cfg},
                x, train=True, compute_dtype=jnp.bfloat16,
                axis_name=None)
            onehot = jax.nn.one_hot(y, logits.shape[-1])
            loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
            return loss, ns

        (loss, ns), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"])
        if distributed:
            if reduce_grads_in_step:
                grads = hvd.allreduce(grads)
            # Stats computed per-shard must be re-replicated before the
            # step returns them under out_specs=P(): ONE fused pmean of
            # the whole batch_stats tree (vs r02's 2 collectives per BN
            # layer at apply time — see docs/PERF_NOTES.md).
            ns = hvd.allreduce(ns)
        updates, new_opt = opt.update(grads, opt_state, state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "batch_stats": ns}, new_opt, loss

    return step


def sync(x):
    """Force completion.  `block_until_ready` alone does not reliably block
    through remote PJRT transports (observed on the axon tunnel), so sync
    with an actual device->host transfer of a scalar."""
    import jax
    import numpy as np
    jax.block_until_ready(x)
    return float(np.asarray(jax.tree_util.tree_leaves(x)[0]).ravel()[0])


def time_steps(compiled, state, opt_state, batch, warmup, iters):
    for _ in range(warmup):
        state, opt_state, loss = compiled(state, opt_state, batch)
    sync(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, opt_state, loss = compiled(state, opt_state, batch)
    sync(loss)
    dt = time.perf_counter() - t0
    return dt / iters, state, opt_state


# ---------------------------------------------------------------------------
# Simulated scaling efficiency child (ResNet-18 on an n-device CPU mesh)
# ---------------------------------------------------------------------------

def run_sim_child(n_devices: int, distributed: bool = True) -> None:
    """Child mode: per-chip img/sec of the framework DP step on an
    n-device virtual CPU mesh.  Prints one JSON line.

    distributed=False runs the identical compute WITHOUT the gradient
    allreduce — the compute-only baseline that isolates per-step
    collective time (reference: the timeline's NEGOTIATE/NCCL phases vs
    compute)."""
    from horovod_tpu.common.util import force_cpu_platform
    force_cpu_platform(n_devices)
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import resnet_init

    hvd.init()
    assert hvd.size() == n_devices
    # Per-chip batch 16: at 8 the fixed gradient-psum cost (ResNet-18's
    # 11M params move regardless of batch) dominates the tiny compute
    # slice and the shared-core measurement wobbles around the target;
    # 16 keeps the compute:collective ratio representative of real
    # configs (per-chip 64-256 on hardware).
    per_chip = 16
    batch = per_chip * n_devices
    v = resnet_init(jax.random.PRNGKey(0), 18, num_classes=100)
    base_opt = optax.sgd(0.01, momentum=0.9)
    # Default pipeline: reverse-availability bucketing + per-bucket fused
    # optimizer apply (hvd.DistributedOptimizer handles the reduction).
    # HOROVOD_BENCH_LEGACY_PIPELINE=1 restores the r05 barriered path
    # (one allreduce of the whole tree, then one global opt.update) for
    # before/after comparison.
    legacy = os.environ.get("HOROVOD_BENCH_LEGACY_PIPELINE") == "1"
    sharded = os.environ.get("HOROVOD_SHARD_OPTIMIZER") == "1"
    quant = bool(os.environ.get("HOROVOD_WIRE_POLICY"))
    guard = os.environ.get("HOROVOD_GUARD") == "1"
    fusedc = os.environ.get("HOROVOD_FUSED_COLLECTIVES") == "1"
    if legacy or not distributed:
        pipeline = "legacy"
    elif sharded:
        pipeline = "sharded"
    elif quant:
        # Overlap pipeline + per-bucket wire policy (docs/WIRE.md): big
        # buckets ride the quantized ring, small stay exact.
        pipeline = "quant"
    elif guard:
        # Overlap pipeline + fused non-finite sentinel (docs/GUARD.md):
        # HOROVOD_GUARD=1 arms the skip-step gate inside the
        # DistributedOptimizer; the delta vs "overlap" is the sentinel
        # cost (one scalar per bucket + one tiny Max-allreduce).
        pipeline = "guard"
    elif fusedc:
        # Overlap pipeline + chunked fused computation-collective
        # pipeline (docs/FUSED_COLLECTIVES.md): each bucket's reduction
        # runs as fused_chunk_bytes chunks whose collectives issue while
        # the rest of the bucket packs; the delta vs "overlap" is the
        # intra-bucket wire time the chunking hides (or the chunking
        # overhead, when negative).
        pipeline = "fused"
    else:
        pipeline = "overlap"
    if pipeline == "sharded":
        # ZeRO-1: reduce-scatter the bucketed grads, update the local
        # optimizer-state shard, allgather params (docs/SHARDED_OPTIMIZER.md).
        opt = hvd.DistributedOptimizer(base_opt, shard_optimizer_states=True)
        step_fn = build_step(opt, v["config"], distributed=True,
                             reduce_grads_in_step=False)
    elif pipeline in ("overlap", "quant", "guard", "fused"):
        opt = hvd.DistributedOptimizer(base_opt, fused_apply=True)
        step_fn = build_step(opt, v["config"], distributed=True,
                             reduce_grads_in_step=False)
    else:
        opt = base_opt
        step_fn = build_step(opt, v["config"], distributed=distributed)
    state = {"params": v["params"], "batch_stats": v["batch_stats"]}
    opt_state = opt.init(state["params"])
    # Per-chip resident inner optimizer-state bytes — the ZeRO-1
    # denominator (shrinks ~n_devices-fold under the sharded pipeline).
    opt_state_bytes = hvd.optimizer_state_bytes(opt_state)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, 32, 32, 3),
                          jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 100)

    step = hvd.data_parallel(step_fn)
    sb = hvd.shard_batch((x, y))
    # More iters at n=1: its ~0.4s steps carry most of the efficiency
    # ratio's run-to-run noise on the shared core.
    iters = 12 if n_devices == 1 else 6
    t, _, _ = time_steps(step, state, opt_state, sb, warmup=2, iters=iters)
    record = {"n": n_devices, "step_time_s": t,
              "pipeline": pipeline,
              "opt_state_bytes": opt_state_bytes,
              "per_chip_img_sec": batch / t / n_devices}
    if pipeline == "quant":
        # Static per-step wire-byte accounting of the active policy over
        # the gradient leaves (same bookkeeping hvd_wire_bytes_saved
        # reports; grads share the param tree's shapes).
        plan = hvd.wire_policy_plan(
            jax.tree_util.tree_leaves(state["params"]))
        record["wire_bytes_saved"] = sum(
            raw - wb for _, _, raw, wb in plan)
        record["wire_bytes_raw"] = sum(raw for _, _, raw, _ in plan)
    if pipeline == "fused":
        # Static per-chunk pipeline schedule over the gradient leaves:
        # chunk counts and the occupancy model (1 - 1/k per bucket —
        # the fraction of a bucket's wire time another chunk's stage
        # covers).  Same bookkeeping the fused_bucket_k timeline
        # instants carry.
        fplan = hvd.fused_pipeline_plan(
            jax.tree_util.tree_leaves(state["params"]))
        ks = [k for _, _, k, _, _ in fplan]
        record["fused_buckets"] = len(fplan)
        record["fused_chunks_total"] = int(sum(ks))
        record["fused_chunk_bytes"] = int(fplan[0][3]) if fplan else 0
        record["fused_occupancy_mean"] = round(
            sum(occ for *_, occ in fplan) / max(1, len(fplan)), 4)
        record["fused_occupancy_max"] = round(
            max((occ for *_, occ in fplan), default=0.0), 4)
    from horovod_tpu.utils import timeline as _tl_mod
    if _tl_mod.get_timeline() is not None:
        # Trace-measured pass (docs/TRACE.md): restart the timeline so
        # the file holds ONLY device-synced steps — the async warmup/
        # timing dispatches above would otherwise pollute the cycle
        # windows `trace analyze` measures — then run per-step-synced
        # iterations; data_parallel marks one CYCLE_n per call.
        trace_iters = 6
        hvd.start_timeline(os.environ["HOROVOD_TIMELINE"],
                           mark_cycles=True)
        for _ in range(trace_iters):
            state, opt_state, loss = step(state, opt_state, sb)
            sync(loss)
        hvd.stop_timeline()
        record["trace_steps"] = trace_iters
    print(json.dumps(record))


def run_zero_bytes_child(n_devices: int) -> None:
    """Child mode: ZeRO ladder memory accounting on an n-device virtual
    CPU mesh — per-chip resident bytes of the gradient accumulator
    (stage 1 vs stage 2, backward_passes_per_step=2) and of the
    parameters (replicated vs stage-3 at-rest shards).  Prints one JSON
    line (docs/SHARDED_OPTIMIZER.md memory model)."""
    from horovod_tpu.common.util import force_cpu_platform
    force_cpu_platform(n_devices)
    import jax
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import resnet_init

    hvd.init()
    assert hvd.size() == n_devices
    params = resnet_init(jax.random.PRNGKey(0), 18, num_classes=100)
    base = optax.sgd(0.01, momentum=0.9)
    o1 = hvd.DistributedOptimizer(base, backward_passes_per_step=2,
                                  early_reduction=True, zero_stage=1)
    o2 = hvd.DistributedOptimizer(base, backward_passes_per_step=2,
                                  zero_stage=2)
    s1 = o1.init(params)
    s2 = o2.init(params)
    g1 = hvd.grad_accum_bytes(s1)
    g2 = hvd.grad_accum_bytes(s2)
    pl = hvd.zero3_placement(params)
    emit({
        "n": n_devices,
        "grad_accum_bytes_stage1": g1,
        "grad_accum_bytes_stage2": g2,
        "grad_accum_reduction": round(g1 / max(1, g2), 4),
        "param_bytes_replicated": pl.full_bytes,
        "param_bytes_resident_stage3": pl.resident_bytes(),
        "param_resident_reduction": round(
            pl.full_bytes / max(1, pl.resident_bytes()), 4),
        "opt_state_bytes_stage1": hvd.optimizer_state_bytes(s1),
    })


def zero_memory_report(timeout: float = 600.0) -> dict:
    """ZeRO ladder memory pipeline: the gradient-accumulator claim at
    n=2 (stage 2 halves it exactly with backward_passes_per_step >= 2)
    and the parameter-residency claim at n=8 (stage 3 keeps ~1/N
    resident outside the live bucket window), each measured in a child
    process on its own virtual mesh."""
    out = {}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for n in (2, 8):
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--zero-bytes-child", str(n)],
            capture_output=True, text=True, timeout=timeout, env=env)
        if r.returncode != 0:
            log(f"zero-bytes child n={n} rc={r.returncode} "
                f"stderr tail: {r.stderr[-1000:]}")
            continue
        rec = json.loads(r.stdout.strip().splitlines()[-1])
        out[f"n{n}"] = rec
        log(f"zero bytes n={n}: grad accum "
            f"{rec['grad_accum_bytes_stage1']} -> "
            f"{rec['grad_accum_bytes_stage2']} "
            f"({rec['grad_accum_reduction']}x, stage 2); params "
            f"{rec['param_bytes_replicated']} -> "
            f"{rec['param_bytes_resident_stage3']} resident "
            f"({rec['param_resident_reduction']}x, stage 3)")
    return out


def run_reshard_child() -> None:
    """Child mode: live-reshard vs checkpoint-restore timing at n=2
    (docs/RESHARD.md).  Two simulated old ranks hold ~4 MB of ZeRO
    shard rows; the live path publishes + fetches through the in-memory
    transport under the default peak ceiling, the legacy path does a
    durable checkpoint save + restore + local restack.  Prints one JSON
    line with both wall times and the measured staging peak."""
    import tempfile

    import numpy as np

    from horovod_tpu.parallel import reshard as rs
    from horovod_tpu.utils.checkpoint import CheckpointManager

    rng = np.random.RandomState(0)
    ge = (1 << 19, 1 << 19)  # two 512k-elem f32 groups = 4 MB total
    n_old = 2
    rows = tuple(rng.randn(n_old, -(-e // n_old)).astype(np.float32)
                 for e in ge)
    peak = rs.default_peak_bytes()

    t = rs.LocalTransport()
    t0 = time.perf_counter()
    for r in range(n_old):
        specs, data = rs.param_streams(rows, ge, n_old, r)
        rs.reshard_streams(specs, data, n_old, 1, r, None, t,
                           tag="bench", peak_bytes=peak)
    specs, _ = rs.param_streams(rows, ge, n_old, 0)
    streams, rep = rs.reshard_streams(
        specs, None, n_old, 1, None, 0, t, tag="bench", peak_bytes=peak)
    live_rows = rs.streams_to_param_rows(
        streams, ge, tuple(r.dtype for r in rows), 1, 0)
    live_ms = (time.perf_counter() - t0) * 1000.0

    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d)
        t0 = time.perf_counter()
        mgr.save(0, {"rows": list(rows)}, force=True)
        restored = mgr.restore(0)
        ck_rows = tuple(rs.reshard_shard_rows(np.asarray(r), e, 1)
                        for r, e in zip(restored["rows"], ge))
        restore_ms = (time.perf_counter() - t0) * 1000.0

    bitwise = all(a.tobytes() == b.tobytes()
                  for a, b in zip(live_rows, ck_rows))
    emit({
        "n_old": n_old, "n_new": 1,
        "state_bytes": int(sum(r.nbytes for r in rows)),
        "live_ms": round(live_ms, 2),
        "restore_ms": round(restore_ms, 2),
        "speedup": round(restore_ms / max(live_ms, 1e-6), 2),
        "peak_bytes": rep.peak_bytes,
        "peak_ceiling": peak,
        "chunks": rep.chunks,
        "bitwise_vs_restore": bitwise,
    })


def reshard_report(timeout: float = 600.0) -> dict:
    """Live-reshard extra: redistribute-vs-restore wall time and the
    measured staging peak at n=2, in a child process
    (docs/RESHARD.md)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--reshard-child"],
        capture_output=True, text=True, timeout=timeout, env=env)
    if r.returncode != 0:
        log(f"reshard child rc={r.returncode} "
            f"stderr tail: {r.stderr[-1000:]}")
        return {}
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    log(f"reshard n=2->1: live {rec['live_ms']} ms vs "
        f"save+restore+restack {rec['restore_ms']} ms "
        f"({rec['speedup']}x), peak {rec['peak_bytes']} / "
        f"{rec['peak_ceiling']} bytes, bitwise="
        f"{rec['bitwise_vs_restore']}")
    return rec


def run_chaos_child() -> None:
    """Runner-launched rank of the chaos bench: one fault-loaded
    `ChaosSoak` (horovod_tpu/faults/chaos.py, docs/CHAOS.md) per rank,
    result JSON written to $HVD_CHAOS_OUT/rank{r}.json."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import horovod_tpu as hvd
    from horovod_tpu.faults.chaos import ChaosSoak

    hvd.init()
    res = ChaosSoak(
        seed=int(os.environ.get("HVD_CHAOS_SEED", "7"))).run()
    with open(os.path.join(os.environ["HVD_CHAOS_OUT"],
                           f"rank{hvd.rank()}.json"), "w") as f:
        json.dump(res, f)
    hvd.shutdown()


def _pctl(xs, q):
    """Nearest-rank percentile of a sorted list."""
    if not xs:
        return None
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


def chaos_report(timeout: float = 600.0) -> dict:
    """Chaos extra: MTTR percentiles + steps-lost-per-injection from a
    real np>=2 fault-loaded soak (HOROVOD_BENCH_CHAOS_NP, default 2)."""
    np_ = int(os.environ.get("HOROVOD_BENCH_CHAOS_NP", "2"))
    out = tempfile.mkdtemp(prefix="bench_chaos_")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["HVD_CHAOS_OUT"] = out
    env.setdefault("HOROVOD_CHAOS_GENERATIONS", "6")
    env.setdefault("HOROVOD_CHAOS_STEPS_PER_GEN", "5")
    env.setdefault("HOROVOD_AUTOTUNE", "1")
    env.setdefault("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    env.setdefault("HOROVOD_TIMELINE", os.path.join(out, "tl.json"))
    env.setdefault("HOROVOD_TIMELINE_ALL_RANKS", "1")
    env.setdefault("HOROVOD_TIMELINE_MARK_CYCLES", "1")
    env.setdefault("HOROVOD_TIMELINE_DISABLE_NATIVE", "1")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
         sys.executable, os.path.abspath(__file__), "--chaos-child"],
        capture_output=True, text=True, timeout=timeout, env=env)
    if r.returncode != 0:
        log(f"chaos fleet rc={r.returncode} "
            f"stderr tail: {r.stderr[-1500:]}")
        return {}
    with open(os.path.join(out, "rank0.json")) as f:
        res = json.load(f)
    events = res["events"]
    mttr = sorted(float(e["mttr_ms"]) for e in events
                  if e["outcome"] == "recovered")
    lost = [int(e["steps_lost"]) for e in events]
    bests = [w["autotune_best"] for w in res["windows"]
             if w.get("autotune_best") is not None]
    return {
        "np": np_,
        "generations": len(res["windows"]),
        "events": len(events),
        "kinds": sorted(res["kinds_injected"]),
        "recovered": sum(1 for e in events
                         if e["outcome"] == "recovered"),
        "degraded": sum(1 for e in events if e["outcome"] == "degraded"),
        "mttr_p50_ms": round(_pctl(mttr, 0.50), 2) if mttr else None,
        "mttr_p99_ms": round(_pctl(mttr, 0.99), 2) if mttr else None,
        "steps_lost_total": sum(lost),
        "steps_lost_per_injection": (round(sum(lost) / len(lost), 3)
                                     if lost else 0.0),
        "loud_reinits": res["loud_reinits"],
        "reactions": res["reactions"],
        "autotune_best_final": bests[-1] if bests else None,
        "split_brain": res["split_brain"],
        "final_digest_mismatch": res["final_digest_mismatch"],
    }


def main_chaos():
    """`bench.py --chaos`: run the chaos extra standalone and append the
    record to BENCH_chaos.json (JSON lines, same provenance stamps and
    HOROVOD_BENCH_CACHE_MAX_AGE_H stale gate as BENCH_serve.json —
    duplicated here because the bench parent never imports the
    package)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(repo, "BENCH_chaos.json")
    prev = None
    if os.path.exists(path):
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        if lines:
            prev = json.loads(lines[-1])
            age_h = (time.time()
                     - prev.get("captured_unix", 0.0)) / 3600.0
            prev["stale"] = age_h > CACHE_MAX_AGE_H
            if prev["stale"]:
                log(f"previous chaos record is {age_h:.1f}h old "
                    f"(> {CACHE_MAX_AGE_H:g}h gate) — not comparing")
    try:
        rec = chaos_report()
    except Exception as e:  # noqa: BLE001
        log(f"chaos bench failed: {type(e).__name__}: {e}")
        rec = {}
    if not rec:
        emit({"bench": "chaos", "error": "chaos soak failed; see stderr"})
        sys.exit(1)
    rec = {"bench": "chaos", **rec}
    if (prev is not None and not prev.get("stale")
            and prev.get("bench") == "chaos"
            and prev.get("mttr_p50_ms") and rec.get("mttr_p50_ms")):
        rec["mttr_p50_vs_prev"] = round(
            rec["mttr_p50_ms"] / prev["mttr_p50_ms"], 3)
    now = time.time()
    rec["captured_unix"] = now
    rec["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime(now))
    with open(path, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")
    log(f"chaos np={rec['np']}: {rec['events']} events "
        f"({rec['recovered']} recovered / {rec['degraded']} degraded), "
        f"MTTR p50/p99 {rec['mttr_p50_ms']}/{rec['mttr_p99_ms']} ms, "
        f"{rec['steps_lost_per_injection']} steps lost/injection, "
        f"{len(rec['kinds'])} fault kinds")
    emit(rec)


def run_autoscale_child() -> None:
    """`bench.py --autoscale-child`: the autoscaler A/B + scale-event
    chaos (horovod_tpu/serve/autoscale.py, docs/AUTOSCALE.md), result
    JSON written to $HVD_AUTOSCALE_OUT.

    For each traffic shape the same seeded trace drives the REAL
    decision core twice — autoscaled vs a static fleet pinned at the
    autoscaled run's MEAN size (same chips, only the control loop
    differs) — and records SLO-violation-minutes and chip-hours.  The
    bursty shape is the acceptance anchor: autoscaling must win on
    violation-minutes at the same mean size.  Then run_scale_chaos
    fires serve.replica_die DURING live grow events on a real replica
    fleet and must report every event recovered digest-verified."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from horovod_tpu.serve.autoscale import (
        AutoscaleConfig,
        run_scale_chaos,
        simulate_autoscale,
    )
    from horovod_tpu.serve.loadgen import make_shaped_trace

    cfg = AutoscaleConfig(min_replicas=1, max_replicas=8,
                          cooldown_steps=4, dwell_steps=2, grow_step=2)
    shapes = {
        "burst": dict(base_every=4.0, burst_every=128, burst_size=80),
        "diurnal": dict(base_every=4.0, period=256, amplitude=0.9),
        "multi_tenant": dict(base_every=4.0),
    }
    ab = {}
    for shape, kw in shapes.items():
        trace = make_shaped_trace(shape, 7, 500, 64, **kw)
        auto = simulate_autoscale(trace, cfg)
        static = simulate_autoscale(
            trace, cfg, static_size=max(1, round(auto["fleet_mean"])))
        ab[shape] = {"autoscaled": auto, "static": static,
                     "violation_minutes_saved": round(
                         static["slo_violation_minutes"]
                         - auto["slo_violation_minutes"], 4)}

    chaos = run_scale_chaos(
        n_events=int(os.environ.get("HVD_AUTOSCALE_EVENTS", "2")),
        seed=0)
    with open(os.environ["HVD_AUTOSCALE_OUT"], "w") as f:
        json.dump({"ab": ab, "scale_chaos": chaos}, f)


def autoscale_report(timeout: float = 600.0) -> dict:
    """Autoscale extra: run the child out-of-process (the parent never
    imports the package) and flatten its record."""
    out = tempfile.mkdtemp(prefix="bench_autoscale_")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["HVD_AUTOSCALE_OUT"] = os.path.join(out, "autoscale.json")
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--autoscale-child"],
        capture_output=True, text=True, timeout=timeout, env=env)
    if r.returncode != 0:
        log(f"autoscale child rc={r.returncode} "
            f"stderr tail: {r.stderr[-1500:]}")
        return {}
    with open(env["HVD_AUTOSCALE_OUT"]) as f:
        res = json.load(f)
    burst = res["ab"]["burst"]
    chaos = res["scale_chaos"]
    return {
        "ab": res["ab"],
        "burst_auto_violation_minutes":
            burst["autoscaled"]["slo_violation_minutes"],
        "burst_static_violation_minutes":
            burst["static"]["slo_violation_minutes"],
        "burst_fleet_mean": burst["autoscaled"]["fleet_mean"],
        "burst_chip_hours": burst["autoscaled"]["chip_hours"],
        "autoscaled_wins_burst":
            burst["autoscaled"]["slo_violation_minutes"]
            < burst["static"]["slo_violation_minutes"],
        "scale_chaos": chaos,
        "scale_events": len(chaos.get("events", [])),
        "scale_events_faulted": sum(
            1 for e in chaos.get("events", []) if e["faulted"]),
        "all_recovered": chaos.get("all_recovered", False),
    }


def main_autoscale():
    """`bench.py --autoscale`: run the autoscale extra standalone and
    append the record to BENCH_autoscale.json (JSON lines, same
    provenance stamps and HOROVOD_BENCH_CACHE_MAX_AGE_H stale gate as
    the other bench files)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(repo, "BENCH_autoscale.json")
    prev = None
    if os.path.exists(path):
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        if lines:
            prev = json.loads(lines[-1])
            age_h = (time.time()
                     - prev.get("captured_unix", 0.0)) / 3600.0
            prev["stale"] = age_h > CACHE_MAX_AGE_H
            if prev["stale"]:
                log(f"previous autoscale record is {age_h:.1f}h old "
                    f"(> {CACHE_MAX_AGE_H:g}h gate) — not comparing")
    try:
        rec = autoscale_report()
    except Exception as e:  # noqa: BLE001
        log(f"autoscale bench failed: {type(e).__name__}: {e}")
        rec = {}
    if not rec:
        emit({"bench": "autoscale",
              "error": "autoscale bench failed; see stderr"})
        sys.exit(1)
    rec = {"bench": "autoscale", **rec}
    if (prev is not None and not prev.get("stale")
            and prev.get("bench") == "autoscale"
            and prev.get("burst_auto_violation_minutes") is not None
            and rec.get("burst_auto_violation_minutes") is not None
            and prev["burst_auto_violation_minutes"] > 0):
        rec["burst_violation_vs_prev"] = round(
            rec["burst_auto_violation_minutes"]
            / prev["burst_auto_violation_minutes"], 3)
    now = time.time()
    rec["captured_unix"] = now
    rec["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime(now))
    with open(path, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")
    log(f"autoscale burst: auto {rec['burst_auto_violation_minutes']} "
        f"vs static {rec['burst_static_violation_minutes']} "
        f"violation-minutes at mean fleet {rec['burst_fleet_mean']} "
        f"(wins={rec['autoscaled_wins_burst']}); scale chaos "
        f"{rec['scale_events']} events "
        f"({rec['scale_events_faulted']} faulted), "
        f"all_recovered={rec['all_recovered']}")
    emit(rec)


def run_obs_child() -> None:
    """`bench.py --obs-child`: sampler-overhead A/B for the telemetry
    history plane (horovod_tpu/metrics/history.py, docs/TELEMETRY.md),
    emitted as one JSON line.

    Arm A runs an instrumented synthetic step loop (counter incs, gauge
    sets, histogram observes — the per-step shape of the real training
    instrumentation) with no sampler; arm B runs the identical loop with
    the background history sampler armed at an aggressive 20 Hz (the
    default cadence is 1 Hz, so this bounds the real overhead from
    above).  Arms are interleaved across repeats and medians compared,
    plus a direct per-sample() micro-measure over the full catalog."""
    import random

    from horovod_tpu.metrics import catalog, history

    rng = random.Random(7)

    def step():
        catalog.steps.inc()
        catalog.critical_path_ms.set(10.0 + rng.random())
        catalog.serve_e2e_latency.observe(0.01 + rng.random() * 0.002)
        catalog.serve_queue_delay.observe(rng.random() * 1e-3)
        # Stand-in compute so the loop is not 100% metrics calls.
        s = 0.0
        for i in range(200):
            s += i * 1e-6
        return s

    n_steps = int(os.environ.get("HVD_OBS_STEPS", "3000"))
    repeats = int(os.environ.get("HVD_OBS_REPEATS", "3"))

    def run_arm(sampled: bool) -> float:
        if sampled:
            history.start_history(interval=0.05)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            step()
        dt = time.perf_counter() - t0
        if sampled:
            history.stop_history()
        return dt

    run_arm(False)  # warmup (interpreter caches, registry children)
    plain, sampled = [], []
    for _ in range(repeats):
        plain.append(run_arm(False))
        sampled.append(run_arm(True))
    plain.sort()
    sampled.sort()
    t_a, t_b = _pctl(plain, 0.5), _pctl(sampled, 0.5)
    overhead_pct = max(0.0, (t_b - t_a) / t_a * 100.0)

    h = history.MetricsHistory(depth=64)
    h.sample()  # prime histogram-delta state
    t0 = time.perf_counter()
    k = 50
    for _ in range(k):
        h.sample()
    per_sample_us = (time.perf_counter() - t0) / k * 1e6
    emit({
        "steps": n_steps,
        "repeats": repeats,
        "step_us": round(t_a / n_steps * 1e6, 2),
        "sampler_overhead_pct": round(overhead_pct, 3),
        "per_sample_us": round(per_sample_us, 1),
        "series_tracked": len(h.series()),
    })


def obs_report(timeout: float = 600.0) -> dict:
    """Observability extra: (a) history-sampler overhead as % of step
    time from the A/B child, (b) anomaly-detection recall from a real
    np=2 fault-loaded soak (the chaos harness doubles as the detector's
    recall fixture — injected faults are ground truth)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--obs-child"],
        capture_output=True, text=True, timeout=timeout, env=env)
    if r.returncode != 0:
        log(f"obs child rc={r.returncode} "
            f"stderr tail: {r.stderr[-1000:]}")
        return {}
    rec = json.loads(r.stdout.strip().splitlines()[-1])

    np_ = int(os.environ.get("HOROVOD_BENCH_CHAOS_NP", "2"))
    out = tempfile.mkdtemp(prefix="bench_obs_")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["HVD_CHAOS_OUT"] = out
    # Same fast-soak shape the tier-1 chaos test uses: 4 straggler-armed
    # generations then a one-shot rotation, so recall has ground truth.
    env.setdefault("HOROVOD_CHAOS_GENERATIONS", "5")
    env.setdefault("HOROVOD_CHAOS_STEPS_PER_GEN", "4")
    env.setdefault("HOROVOD_STRAGGLER_PATIENCE", "2")
    env.setdefault("HOROVOD_STRAGGLER_COOLDOWN", "1")
    env.setdefault("HOROVOD_AUTOTUNE", "1")
    env.setdefault("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    env.setdefault("HOROVOD_TIMELINE", os.path.join(out, "tl.json"))
    env.setdefault("HOROVOD_TIMELINE_ALL_RANKS", "1")
    env.setdefault("HOROVOD_TIMELINE_MARK_CYCLES", "1")
    env.setdefault("HOROVOD_TIMELINE_DISABLE_NATIVE", "1")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
         sys.executable, os.path.abspath(__file__), "--chaos-child"],
        capture_output=True, text=True, timeout=timeout, env=env)
    if r.returncode != 0:
        log(f"obs chaos fleet rc={r.returncode} "
            f"stderr tail: {r.stderr[-1500:]}")
        return {}
    with open(os.path.join(out, "rank0.json")) as f:
        anom = json.load(f).get("anomaly", {})
    rec.update({
        "np": np_,
        "detection_recall": anom.get("recall"),
        "detected_kinds": anom.get("detected_kinds", []),
        "injected_kinds": anom.get("injected_kinds", []),
        "false_positives": anom.get("false_positives"),
    })
    return rec


def main_obs():
    """`bench.py --obs`: run the observability extra standalone and
    append the record to BENCH_obs.json (JSON lines, same provenance
    stamps and HOROVOD_BENCH_CACHE_MAX_AGE_H stale gate as
    BENCH_chaos.json)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(repo, "BENCH_obs.json")
    prev = None
    if os.path.exists(path):
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        if lines:
            prev = json.loads(lines[-1])
            age_h = (time.time()
                     - prev.get("captured_unix", 0.0)) / 3600.0
            prev["stale"] = age_h > CACHE_MAX_AGE_H
            if prev["stale"]:
                log(f"previous obs record is {age_h:.1f}h old "
                    f"(> {CACHE_MAX_AGE_H:g}h gate) — not comparing")
    try:
        rec = obs_report()
    except Exception as e:  # noqa: BLE001
        log(f"obs bench failed: {type(e).__name__}: {e}")
        rec = {}
    if not rec:
        emit({"bench": "obs", "error": "obs bench failed; see stderr"})
        sys.exit(1)
    rec = {"bench": "obs", **rec}
    rec["overhead_budget_pct"] = 2.0
    rec["overhead_ok"] = rec["sampler_overhead_pct"] <= 2.0
    if (prev is not None and not prev.get("stale")
            and prev.get("bench") == "obs"
            and prev.get("per_sample_us") and rec.get("per_sample_us")):
        rec["per_sample_vs_prev"] = round(
            rec["per_sample_us"] / prev["per_sample_us"], 3)
    now = time.time()
    rec["captured_unix"] = now
    rec["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime(now))
    with open(path, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")
    log(f"obs: sampler overhead {rec['sampler_overhead_pct']}% of step "
        f"time (budget 2%, ok={rec['overhead_ok']}), "
        f"{rec['per_sample_us']}us/sample over "
        f"{rec['series_tracked']} series; detection recall "
        f"{rec['detection_recall']} ({len(rec['detected_kinds'])}/"
        f"{len(rec['injected_kinds'])} kinds, "
        f"{rec['false_positives']} false positives)")
    emit(rec)


def _load_trace_core():
    """The fleet tracer's analyzer (horovod_tpu/trace/core.py), loaded
    by file path so the bench parent never imports the package (and so
    never pulls jax in — the same rule hvdlint follows)."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "horovod_tpu", "trace", "core.py")
    spec = importlib.util.spec_from_file_location("_hvd_trace_core", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# Side channel: the full JSON record of the most recent sim child, so
# callers that go through the `_run_sim` timing seam (the function the
# stats tests monkeypatch) can still read non-timing fields like
# opt_state_bytes.  None when the last probe failed or was stubbed out.
_LAST_SIM_RECORD = None


def _run_sim_record(n: int, distributed: bool, timeout: float,
                    legacy: bool = False, sharded: bool = False,
                    quant: bool = False, guard: bool = False,
                    fused: bool = False, timeline: "str | None" = None):
    """Run one sim child; return its full JSON record (or None).
    `timeline` arms HOROVOD_TIMELINE in the child so it appends the
    trace-measured synced pass (see run_sim_child)."""
    global _LAST_SIM_RECORD
    _LAST_SIM_RECORD = None
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("HOROVOD_SHARD_OPTIMIZER", None)
    env.pop("HOROVOD_WIRE_POLICY", None)
    env.pop("HOROVOD_GUARD", None)
    env.pop("HOROVOD_FUSED_COLLECTIVES", None)
    env.pop("HOROVOD_TIMELINE", None)
    env.pop("HOROVOD_TIMELINE_MARK_CYCLES", None)
    if timeline:
        env["HOROVOD_TIMELINE"] = timeline
        env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    if legacy:
        env["HOROVOD_BENCH_LEGACY_PIPELINE"] = "1"
    if sharded:
        env["HOROVOD_SHARD_OPTIMIZER"] = "1"
    if quant:
        env["HOROVOD_WIRE_POLICY"] = "auto"
    if guard:
        env["HOROVOD_GUARD"] = "1"
    if fused:
        env["HOROVOD_FUSED_COLLECTIVES"] = "1"
    cmd = [sys.executable, os.path.abspath(__file__), "--sim-child", str(n)]
    if not distributed:
        cmd.append("--no-dist")
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        log(f"sim-scaling child n={n} timed out")
        return None
    if r.returncode != 0:
        log(f"sim-scaling child n={n} rc={r.returncode} "
            f"stderr tail: {r.stderr[-500:]}")
        return None
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    _LAST_SIM_RECORD = rec
    return rec


def _run_sim(n: int, distributed: bool, timeout: float,
             legacy: bool = False, sharded: bool = False,
             quant: bool = False, guard: bool = False,
             fused: bool = False):
    rec = _run_sim_record(n, distributed, timeout, legacy=legacy,
                          sharded=sharded, quant=quant, guard=guard,
                          fused=fused)
    return None if rec is None else rec["step_time_s"]


def sim_scaling_efficiency(timeout: float = 600.0,
                           runs: "int | None" = None):
    """Simulated scaling efficiency on the virtual CPU mesh —
    gate-quality estimator.

    The n virtual devices share the host's physical cores, so the ideal
    n=8 step (global batch 8x) takes 8x the n=1 step's wall time; any
    extra time is collective/framework overhead.  Efficiency is therefore
    8*T1/T8 — the shared-core analog of per-chip throughput retention on
    real hardware.

    Estimator (tightened per the r04 verdict's gate requirement): the
    per-chip batch is pinned at 16 (see run_sim_child) and `runs` >= 7
    PAIRED (t1, t8) samples are collected — pairing adjacent-in-time
    runs cancels slow host-load drift.  A pair with eff > 1.0 is
    physically impossible on the shared-core mesh (contention inflated
    its t1) and is REJECTED as invalid rather than kept or clamped —
    clamping would bias the center up exactly when the host is loaded,
    keeping it would blow the spread with a value known to be noise.
    The reported center is the TRIMMED median (drop the min and max
    pair, median of the rest), spread is the central-3 order-statistic
    spread, and a bootstrap percentile CI (2.5/97.5, deterministic
    seed) of the trimmed median ships alongside so the >=0.90 gate can
    be read against an interval, not a point.  Returns
    (median, spread, effs, ci, n_rejected, extras) where `extras` is a
    dict with the collective-share decomposition.

    Collective share is T8(dist) - T8(no dist) — the same
    decomposition the reference's timeline gives per tensor — measured
    for BOTH pipelines: the overlap-aware default (reverse-availability
    buckets + fused per-bucket apply) and the legacy barriered path
    (HOROVOD_BENCH_LEGACY_PIPELINE), so the record carries a
    before/after comparison of how much per-step time the collectives
    cost under each.
    """
    global _LAST_SIM_RECORD
    import numpy as _np

    if runs is None:
        runs = int(os.environ.get("HOROVOD_BENCH_SIM_RUNS", "7"))
    max_runs = max(runs,
                   int(os.environ.get("HOROVOD_BENCH_SIM_MAX_RUNS", "9")))
    effs, t1s, t8s = [], [], []
    opt_bytes_repl = None
    rejected = 0
    attempts, max_attempts = 0, 2 * max_runs + 4
    while len(effs) < runs and attempts < max_attempts:
        attempts += 1
        t1 = _run_sim(1, True, timeout)
        if t1 is None:
            # Don't pay the (much longer) n=8 child for a pair that is
            # already dead; retry, bounded by max_attempts so a broken
            # mesh can't loop.
            log(f"sim-scaling attempt {attempts}: n=1 child failed, "
                f"retrying")
            continue
        _LAST_SIM_RECORD = None
        t8 = _run_sim(8, True, timeout)
        if t8 is None:
            log(f"sim-scaling attempt {attempts}: n=8 child failed, "
                f"retrying")
            continue
        if _LAST_SIM_RECORD is not None:
            opt_bytes_repl = _LAST_SIM_RECORD.get("opt_state_bytes",
                                                  opt_bytes_repl)
        eff = 8.0 * t1 / t8
        if eff > 1.0:
            # Superlinear scaling cannot happen on a shared-core mesh:
            # the pair's t1 was inflated by host contention.  Invalid
            # measurement, not an unusually good one — reject it (r04
            # verdict: "discard eff > 1.0 pairs as invalid").
            rejected += 1
            log(f"sim-scaling attempt {attempts}: eff {eff:.4f} > 1.0 "
                f"(contention-inflated t1) — pair rejected")
            continue
        log(f"sim-scaling pair {len(effs)}: n1={t1*1e3:.1f} ms "
            f"n8={t8*1e3:.1f} ms -> eff {eff:.4f}")
        effs.append(eff)
        t1s.append(t1)
        t8s.append(t8)
        # Adaptive widening: transient host contention shows up as a
        # blown spread; extra pairs let the trimmed median reject more
        # outliers (gate asks spread < 0.03 — r04 verdict task 4).
        if (len(effs) == runs and runs < max_runs
                and max(effs) - min(effs) > 0.03):
            log(f"sim-scaling: spread {max(effs) - min(effs):.4f} > 0.03 "
                f"after {runs} pairs; widening to {max_runs}")
            runs = max_runs
    if len(effs) < 3:
        log(f"sim-scaling: only {len(effs)} valid pairs "
            f"({rejected} rejected) — no estimate")
        return None
    extras = {}
    t8_nodist = _run_sim(8, False, timeout)
    if t8_nodist is not None and t8s:
        t8m = sorted(t8s)[len(t8s) // 2]
        share = (t8m - t8_nodist) / t8m
        log(f"sim-scaling n=8 compute-only: {t8_nodist*1e3:.1f} ms/step "
            f"-> collective share {(t8m - t8_nodist)*1e3:.1f} ms/step "
            f"({100 * share:.1f}%)")
        extras["t8_ms"] = round(t8m * 1e3, 1)
        extras["t8_nodist_ms"] = round(t8_nodist * 1e3, 1)
        extras["collective_share"] = round(share, 4)
        # Before/after: the legacy barriered pipeline's n=8 step on the
        # same mesh, timed back-to-back so host load is comparable.
        t8_legacy = _run_sim(8, True, timeout, legacy=True)
        if t8_legacy is not None:
            legacy_share = (t8_legacy - t8_nodist) / t8_legacy
            log(f"sim-scaling n=8 legacy pipeline: {t8_legacy*1e3:.1f} "
                f"ms/step -> collective share "
                f"{(t8_legacy - t8_nodist)*1e3:.1f} ms/step "
                f"({100 * legacy_share:.1f}%)")
            extras["t8_legacy_ms"] = round(t8_legacy * 1e3, 1)
            extras["collective_share_legacy"] = round(legacy_share, 4)
        # ZeRO-1 pipeline: n=8 step with sharded optimizer state
        # (reduce-scatter + local shard update + param allgather), plus
        # the replicated-vs-sharded per-chip state-bytes comparison the
        # memory claim rests on (docs/SHARDED_OPTIMIZER.md).
        _LAST_SIM_RECORD = None
        t8_sharded = _run_sim(8, True, timeout, sharded=True)
        rec_sharded = _LAST_SIM_RECORD
        if t8_sharded is not None:
            sharded_share = (t8_sharded - t8_nodist) / t8_sharded
            log(f"sim-scaling n=8 sharded pipeline: {t8_sharded*1e3:.1f} "
                f"ms/step -> collective share "
                f"{(t8_sharded - t8_nodist)*1e3:.1f} ms/step "
                f"({100 * sharded_share:.1f}%)")
            extras["t8_sharded_ms"] = round(t8_sharded * 1e3, 1)
            extras["collective_share_sharded"] = round(sharded_share, 4)
            sb = (rec_sharded.get("opt_state_bytes")
                  if rec_sharded is not None else None)
            rb = opt_bytes_repl
            if sb and rb:
                log(f"sim-scaling opt-state bytes/chip: replicated {rb} "
                    f"-> sharded {sb} ({rb / sb:.1f}x smaller)")
                extras["opt_state_bytes_replicated"] = int(rb)
                extras["opt_state_bytes_sharded"] = int(sb)
        # Quantized-wire pipeline: n=8 step with HOROVOD_WIRE_POLICY=auto
        # (big gradient buckets ride the int8 ring, small stay exact —
        # docs/WIRE.md), plus the static wire-byte savings of the policy.
        t8_quant = _run_sim(8, True, timeout, quant=True)
        rec_quant = _LAST_SIM_RECORD
        if t8_quant is not None:
            quant_share = (t8_quant - t8_nodist) / t8_quant
            log(f"sim-scaling n=8 quant pipeline: {t8_quant*1e3:.1f} "
                f"ms/step -> collective share "
                f"{(t8_quant - t8_nodist)*1e3:.1f} ms/step "
                f"({100 * quant_share:.1f}%)")
            extras["t8_quant_ms"] = round(t8_quant * 1e3, 1)
            extras["collective_share_quant"] = round(quant_share, 4)
            saved = (rec_quant.get("wire_bytes_saved")
                     if rec_quant is not None else None)
            raw = (rec_quant.get("wire_bytes_raw")
                   if rec_quant is not None else None)
            if saved and raw:
                log(f"sim-scaling wire bytes/step: raw {raw} -> saved "
                    f"{saved} ({raw / (raw - saved):.1f}x less on the "
                    "wire)")
                extras["wire_bytes_saved"] = int(saved)
                extras["wire_bytes_raw"] = int(raw)
        # Training-health guardian: the same overlap pipeline with the
        # fused non-finite sentinel + skip-step gate armed
        # (HOROVOD_GUARD=1, docs/GUARD.md).  The delta vs the plain
        # overlap median is the no-fault guard overhead — the GUARD.md
        # claim is that it stays within ~1% of the step.
        t8_guard = _run_sim(8, True, timeout, guard=True)
        if t8_guard is not None:
            overhead = (t8_guard - t8m) / t8m
            log(f"sim-scaling n=8 guard pipeline: {t8_guard*1e3:.1f} "
                f"ms/step -> sentinel overhead "
                f"{(t8_guard - t8m)*1e3:+.1f} ms/step "
                f"({100 * overhead:+.1f}%)")
            extras["t8_guard_ms"] = round(t8_guard * 1e3, 1)
            extras["guard_overhead"] = round(overhead, 4)
        # Fused computation-collective pipeline: the overlap path with
        # HOROVOD_FUSED_COLLECTIVES=1 (docs/FUSED_COLLECTIVES.md) —
        # bucket reductions software-pipelined in fused_chunk_bytes
        # chunks.  collective_share_fused vs collective_share is the
        # intra-bucket wire time the chunking hides; the per-chunk
        # occupancy stats ship from the child's static schedule.
        _LAST_SIM_RECORD = None
        t8_fused = _run_sim(8, True, timeout, fused=True)
        rec_fused = _LAST_SIM_RECORD
        if t8_fused is not None:
            fused_share = (t8_fused - t8_nodist) / t8_fused
            log(f"sim-scaling n=8 fused pipeline: {t8_fused*1e3:.1f} "
                f"ms/step -> collective share "
                f"{(t8_fused - t8_nodist)*1e3:.1f} ms/step "
                f"({100 * fused_share:.1f}%)")
            extras["t8_fused_ms"] = round(t8_fused * 1e3, 1)
            extras["collective_share_fused"] = round(fused_share, 4)
            if rec_fused is not None:
                for key in ("fused_buckets", "fused_chunks_total",
                            "fused_chunk_bytes", "fused_occupancy_mean",
                            "fused_occupancy_max"):
                    if key in rec_fused:
                        extras[key] = rec_fused[key]
                if "fused_occupancy_mean" in rec_fused:
                    log(f"sim-scaling fused pipeline occupancy: mean "
                        f"{rec_fused['fused_occupancy_mean']:.3f} max "
                        f"{rec_fused['fused_occupancy_max']:.3f} over "
                        f"{rec_fused.get('fused_chunks_total', 0)} "
                        f"chunks in {rec_fused.get('fused_buckets', 0)} "
                        f"buckets")

        # Trace-MEASURED attribution (docs/TRACE.md): re-run the n=8
        # dist/no-dist pair with the timeline armed; the fleet tracer's
        # analyzer reads the per-step critical path from device-synced
        # CYCLE windows instead of wall-clock subtraction.  The sim mesh
        # is one process, so the cross-rank skew component is
        # structurally zero here — skew_share becomes meaningful on
        # multi-process (np>=2) timelines.  Gated on a real child record
        # from the probes above: a stubbed/recordless run has no sim
        # children to re-launch.
        if _LAST_SIM_RECORD is not None or rec_fused is not None:
            try:
                tdir = tempfile.mkdtemp(prefix="hvd_bench_trace_")
                dist_tl = os.path.join(tdir, "dist.json")
                nodist_tl = os.path.join(tdir, "nodist.json")
                _run_sim_record(8, True, timeout, timeline=dist_tl)
                _run_sim_record(8, False, timeout, timeline=nodist_tl)
                tc = _load_trace_core()
                cp_d = tc.analyze([dist_tl])["summary"]
                cp_n = tc.analyze([nodist_tl])["summary"]
                d, nd = (cp_d["critical_path_ms_median"],
                         cp_n["critical_path_ms_median"])
                if d > 0 and nd > 0:
                    extras["critical_path_ms_measured"] = round(d, 1)
                    extras["collective_share_measured"] = round(
                        max(0.0, 1.0 - nd / d), 4)
                    extras["skew_share"] = cp_d["skew_share"]
                    log(f"sim-scaling trace-measured: critical path "
                        f"{d:.1f} ms/step, collective share "
                        f"{100 * extras['collective_share_measured']:.1f}"
                        f"% (measured), skew share "
                        f"{100 * extras['skew_share']:.1f}%")
            except Exception as e:  # noqa: BLE001 — must not sink bench
                log(f"sim-scaling trace-measured attribution "
                    f"skipped: {e}")

    def _trimmed_median(vals):
        s = _np.sort(_np.asarray(vals))
        if len(s) >= 5:
            s = s[1:-1]                       # drop min and max pair
        return float(_np.median(s))

    median = _trimmed_median(effs)
    s = sorted(effs)
    if len(s) >= 5:
        # Spread over the central 3 order statistics — the agreement of
        # the values the trimmed median rests on (the raw per-run list
        # still ships in the JSON for transparency).
        mid = (len(s) - 3) // 2
        spread = s[mid + 2] - s[mid]
    else:
        spread = max(effs) - min(effs)
    # Bootstrap percentile CI of the trimmed median.  Deterministic
    # seed: the interval must be a function of the data, not the run.
    rng = _np.random.default_rng(0)
    arr = _np.asarray(effs)
    boots = [_trimmed_median(rng.choice(arr, size=len(arr)))
             for _ in range(2000)]
    ci = (float(_np.percentile(boots, 2.5)),
          float(_np.percentile(boots, 97.5)))
    log(f"sim-scaling: trimmed median {median:.4f}, spread "
        f"{spread:.4f}, CI [{ci[0]:.4f}, {ci[1]:.4f}] over "
        f"{len(effs)} valid pairs ({rejected} rejected)")
    return median, spread, effs, ci, rejected, extras


# ---------------------------------------------------------------------------
# Transformer tok/s (flagship model, single chip)
# ---------------------------------------------------------------------------

def run_transformer_bench(d_model=512, seq=1024, batch=8, layers=8) -> float:
    """tok/s of one fwd+bwd+update step of the flagship transformer
    (dense config) on the current device — the long-context flagship's
    single-chip number next to the ResNet headline."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models import (
        TransformerConfig, transformer_init, transformer_ref_loss,
    )

    cfg = TransformerConfig(
        vocab_size=8192, d_model=d_model, n_heads=d_model // 64,
        d_head=64, d_ff=4 * d_model, n_layers=layers)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)
    x, y = tokens[:, :-1], tokens[:, 1:]

    def step(carry, batch_xy):
        params, opt_state = carry
        xb, yb = batch_xy

        def loss_fn(p):
            return transformer_ref_loss(p, xb, yb, cfg)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state), loss

    # Megastep (utils/megastep.py): k steps per dispatch amortizes the
    # fixed host->device dispatch latency, which the r04 device trace
    # measured at ~13 ms of a 59 ms step on this link.  k=8 by default;
    # HOROVOD_BENCH_MEGASTEP=1 restores one-dispatch-per-step timing.
    from horovod_tpu.utils.megastep import repeat_steps

    k = int(os.environ.get("HOROVOD_BENCH_MEGASTEP", "8"))
    fused = repeat_steps(step, k)
    carry = (params, opt_state)

    warmup, iters = 2, 4
    for _ in range(warmup):
        carry, loss = fused(carry, (x, y))
    sync(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry, loss = fused(carry, (x, y))
    sync(loss)
    dt = (time.perf_counter() - t0) / (iters * k)
    return batch * seq / dt


# ---------------------------------------------------------------------------
# Keras-path measurement (BASELINE config 3: TF2 Keras DistributedOptimizer)
# ---------------------------------------------------------------------------

def _keras_model_and_data():
    import numpy as np
    import tensorflow as tf

    tf.random.set_seed(0)
    batch = 64
    x = np.random.randn(batch, 28, 28, 1).astype("float32")
    y = np.random.randint(0, 10, (batch,))
    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, activation="relu",
                               input_shape=(28, 28, 1)),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Conv2D(32, 3, activation="relu"),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10),
    ])
    return model, x, y, batch


def _time_keras(model, x, y, batch, warmup=2, iters=8) -> float:
    for _ in range(warmup):
        model.train_on_batch(x, y)
    t0 = time.perf_counter()
    for _ in range(iters):
        model.train_on_batch(x, y)
    return batch * iters / (time.perf_counter() - t0)


def run_keras_bench():
    """(distributed_img_sec, plain_img_sec) of the Keras frontend path:
    a small convnet trained through
    hvd.tensorflow.keras.DistributedOptimizer, next to the IDENTICAL
    model/compile WITHOUT horovod on the same host — the denominator
    that makes the bridge overhead falsifiable (r03 verdict task 5;
    reference: pytorch_synthetic_benchmark.py's per-rank + total img/s
    reporting discipline)."""
    import tensorflow as tf

    import horovod_tpu.tensorflow.keras as hvd_k

    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)

    model, x, y, batch = _keras_model_and_data()
    model.compile(optimizer=tf.keras.optimizers.SGD(0.01), loss=loss_fn)
    plain = _time_keras(model, x, y, batch)

    model, x, y, batch = _keras_model_and_data()
    opt = hvd_k.DistributedOptimizer(tf.keras.optimizers.SGD(0.01))
    model.compile(optimizer=opt, loss=loss_fn)
    dist = _time_keras(model, x, y, batch)
    return dist, plain


# ---------------------------------------------------------------------------
# Main bench
# ---------------------------------------------------------------------------

def run_bench(platform: str) -> dict:
    # Experiment hook: extra XLA flags (e.g. latency-hiding scheduler
    # sweeps) without editing the harness.
    extra_flags = os.environ.get("HOROVOD_BENCH_XLA_FLAGS")
    if extra_flags:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + extra_flags).strip()
    if platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import resnet_init

    hvd.init()
    actual = jax.devices()[0].platform
    on_tpu = actual == "tpu"
    # Reference benchmark: 224x224 synthetic images (docs/benchmarks.rst /
    # pytorch_synthetic_benchmark.py).  The reference's batch 64 is a
    # GPU-era choice; the v5e MXU wants larger batches (sweep in
    # docs/PERF_NOTES.md: 64→2131, 128→2398, 256→2416 img/s/chip), so
    # the TPU default is 256.  HOROVOD_BENCH_BATCH overrides.
    batch = int(os.environ.get("HOROVOD_BENCH_BATCH", 0)) or \
        (256 if on_tpu else 4)
    image = 224 if on_tpu else 64
    warmup, iters = (5, 20) if on_tpu else (2, 3)
    log(f"platform={actual} devices={len(jax.devices())} "
        f"batch={batch} image={image}")

    rng = jax.random.PRNGKey(42)
    v = resnet_init(rng, 50, num_classes=1000)
    cfg = v["config"]
    opt = optax.sgd(0.0125, momentum=0.9)

    x = jax.random.normal(jax.random.PRNGKey(0), (batch, image, image, 3),
                          jnp.bfloat16).astype(jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, 1000)

    def fresh_state():
        vv = resnet_init(rng, 50, num_classes=1000)
        st = {"params": vv["params"], "batch_stats": vv["batch_stats"]}
        return st, opt.init(st["params"])

    # --- framework path: one SPMD program over the mesh ---
    state, opt_state = fresh_state()
    fw_step = hvd.data_parallel(build_step(opt, cfg, distributed=True))
    sb = hvd.shard_batch((x, y))
    t_fw, _, _ = time_steps(fw_step, state, opt_state, sb, warmup, iters)
    fw_imgsec = batch / t_fw / hvd.size()  # per chip
    log(f"framework: {t_fw*1e3:.1f} ms/step, {fw_imgsec:.1f} img/s/chip")

    # --- raw-JAX baseline: same work, plain jit, no framework ---
    state, opt_state = fresh_state()
    raw_step = jax.jit(build_step(opt, cfg, distributed=False),
                       donate_argnums=(0, 1))
    t_raw, _, _ = time_steps(raw_step, state, opt_state, (x, y),
                             warmup, iters)
    raw_imgsec = batch / t_raw
    log(f"raw jax:   {t_raw*1e3:.1f} ms/step, {raw_imgsec:.1f} img/s/chip")

    # --- Keras frontend path (BASELINE config 3) ---
    keras_img_sec = keras_plain = None
    try:
        keras_img_sec, keras_plain = run_keras_bench()
        log(f"keras_img_sec: {keras_img_sec:.1f} img/s through "
            f"DistributedOptimizer vs plain-Keras {keras_plain:.1f} img/s "
            f"-> keras_vs_baseline {keras_img_sec / keras_plain:.4f}")
    except Exception as e:  # noqa: BLE001 — keras path must not sink bench
        log(f"keras bench failed: {type(e).__name__}: {e}")

    # --- transformer tok/s (flagship model, stderr-visible extra) ---
    tfm_tok_s = None
    if on_tpu:
        try:
            tfm_tok_s = run_transformer_bench()
            log(f"transformer_tok_s: {tfm_tok_s:.0f} tok/s "
                f"(1-chip fwd+bwd, d512 T1024 bf16)")
        except Exception as e:  # noqa: BLE001 — extras must not sink bench
            log(f"transformer bench failed: {type(e).__name__}: {e}")

    out = {
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(fw_imgsec, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(fw_imgsec / raw_imgsec, 4),
        # Makes a CPU-fallback run (wedged accelerator at bench time)
        # unmistakable in the recorded JSON.
        "platform": actual,
    }
    if keras_img_sec is not None:
        out["keras_img_sec"] = round(keras_img_sec, 1)
        if keras_plain:
            out["keras_vs_baseline"] = round(keras_img_sec / keras_plain, 4)
    if tfm_tok_s is not None:
        out["transformer_tok_s"] = round(tfm_tok_s, 0)
    return out


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--sim-child":
        run_sim_child(int(sys.argv[2]),
                      distributed="--no-dist" not in sys.argv)
        return

    result = None
    try:
        platform = probe_accelerator()
        # The main bench runs in a subprocess too: even a successful probe
        # does not guarantee the *next* backend init won't wedge, and a
        # killable child lets us retry on CPU.
        env = dict(os.environ)
        if platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
        r = None
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--bench-child",
                 platform],
                capture_output=True, text=True, timeout=1800, env=env)
        except subprocess.TimeoutExpired:
            log(f"bench child on {platform} timed out")
        if r is not None and r.returncode == 0:
            log(r.stderr[-2000:])
            result = json.loads(r.stdout.strip().splitlines()[-1])
        else:
            if r is not None:
                log(f"bench child rc={r.returncode} "
                    f"stderr tail: {r.stderr[-2000:]}")
            if platform != "cpu":
                log("retrying bench on CPU host platform")
                env["JAX_PLATFORMS"] = "cpu"
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--bench-child", "cpu"],
                    capture_output=True, text=True, timeout=1800, env=env)
                log(r.stderr[-2000:])
                if r.returncode == 0:
                    result = json.loads(r.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        log(f"bench failed: {type(e).__name__}: {e}")

    if result is not None and result.get("platform") == "tpu":
        # A live on-chip run is the new last-known-good.
        result["provenance"] = "live"
        store_cache({k: v for k, v in result.items() if k != "provenance"})
    elif result is not None:
        # The bench RAN but only on the CPU host platform — accelerator
        # unreachable (wedged tunnel).  The record still carries the
        # last-known-good ON-CHIP measurement, provenance-marked, with
        # this run's live CPU numbers attached as diagnostics.  (r03
        # verdict task 1: a wedged chip degrades the record's freshness,
        # not its existence.)  A bench that CRASHED (result None) is NOT
        # papered over: it falls through to the error record + exit 1.
        cached = load_cache()
        if cached is not None and cached.get("platform") == "tpu":
            live_cpu = result
            result = {k: v for k, v in cached.items()
                      if k != "captured_unix"}
            result["provenance"] = "cached"
            age_h = (time.time() - cached.get(
                "captured_unix", time.time())) / 3600.0
            result["stale_hours"] = round(age_h, 1)
            if age_h > CACHE_MAX_AGE_H:
                result["stale"] = True
                log(f"WARNING: cached on-chip record is STALE "
                    f"({age_h:.1f} h old > "
                    f"HOROVOD_BENCH_CACHE_MAX_AGE_H="
                    f"{CACHE_MAX_AGE_H:g} h); captured "
                    f"{cached.get('captured_utc')} — re-run on the "
                    "accelerator to refresh")
            else:
                log(f"accelerator unreachable: reusing on-chip record "
                    f"from {cached.get('captured_utc')} ({age_h:.1f} h "
                    f"old, within the {CACHE_MAX_AGE_H:g} h freshness "
                    "window)")
            result["live_cpu_img_sec_per_chip"] = live_cpu.get("value")
        else:
            result["provenance"] = "live"

    if result is None:
        emit({"metric": "resnet50_synthetic_img_sec_per_chip", "value": 0,
              "unit": "img/sec/chip", "vs_baseline": 0,
              "error": "benchmark failed; see stderr"})
        sys.exit(1)

    # Sim scaling always runs live on the CPU host mesh (chip-independent).
    try:
        eff = sim_scaling_efficiency()
    except Exception as e:  # noqa: BLE001
        log(f"sim scaling failed: {type(e).__name__}: {e}")
        eff = None
    if eff is not None:
        median, spread, effs, ci, rejected, extras = eff
        # eff > 1.0 pairs were rejected inside the estimator, so the
        # trimmed median is already <= 1.0 by construction.
        result["scaling_eff_sim8"] = round(median, 4)
        result["scaling_eff_sim8_spread"] = round(spread, 4)
        result["scaling_eff_sim8_runs"] = [round(e, 4) for e in effs]
        result["scaling_eff_sim8_ci"] = [round(ci[0], 4),
                                         round(ci[1], 4)]
        result["scaling_eff_sim8_rejected"] = rejected
        if extras:
            # Collective-share decomposition under the overlap pipeline
            # (default) and the legacy barriered pipeline (before/after).
            result["sim8_collective_share"] = extras

    # ZeRO ladder memory accounting (chip-independent, analytic).
    try:
        zb = zero_memory_report()
    except Exception as e:  # noqa: BLE001
        log(f"zero bytes report failed: {type(e).__name__}: {e}")
        zb = None
    if zb:
        result["zero_bytes"] = zb

    # Live-reshard vs checkpoint-restore timing (host-side, n=2).
    try:
        rr = reshard_report()
    except Exception as e:  # noqa: BLE001
        log(f"reshard report failed: {type(e).__name__}: {e}")
        rr = None
    if rr:
        result["reshard"] = rr

    emit(result)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--zero-bytes-child":
        run_zero_bytes_child(int(sys.argv[2]))
    elif len(sys.argv) >= 2 and sys.argv[1] == "--reshard-child":
        run_reshard_child()
    elif len(sys.argv) >= 2 and sys.argv[1] == "--chaos-child":
        run_chaos_child()
    elif len(sys.argv) >= 2 and sys.argv[1] == "--chaos":
        main_chaos()
    elif len(sys.argv) >= 2 and sys.argv[1] == "--autoscale-child":
        run_autoscale_child()
    elif len(sys.argv) >= 2 and sys.argv[1] == "--autoscale":
        main_autoscale()
    elif len(sys.argv) >= 2 and sys.argv[1] == "--obs-child":
        run_obs_child()
    elif len(sys.argv) >= 2 and sys.argv[1] == "--obs":
        main_obs()
    elif len(sys.argv) >= 3 and sys.argv[1] == "--bench-child":
        emit(run_bench(sys.argv[2]))
    else:
        main()
