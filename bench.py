"""Headline benchmark: ResNet-50 synthetic data, img/sec per chip.

Mirrors the reference's `examples/pytorch/pytorch_synthetic_benchmark.py`
(SURVEY.md §6, BASELINE.json metric "ResNet-50 img/sec/chip"): synthetic
images, SGD-momentum, train-mode batch norm, warmup then timed iterations.

TPU-first differences from the reference harness:
  - one compiled SPMD step (gradient allreduce fused into the step program)
    instead of eager grad hooks + background negotiation;
  - bf16 compute / f32 params;
  - input donation so weights update in place in HBM.

`vs_baseline` is framework-vs-raw-JAX on identical work: the same model,
optimizer, and shapes stepped through plain `jax.jit` with no distributed
wrapper.  1.0 means the framework's distributed machinery adds zero
overhead on one chip; >1.0 means the framework path is faster (fusion wins).

Prints exactly ONE JSON line on stdout; all diagnostics go to stderr.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_step(opt, cfg, distributed: bool):
    from horovod_tpu.models import resnet_apply
    import horovod_tpu as hvd

    def step(state, opt_state, batch):
        x, y = batch

        def loss_fn(p):
            logits, ns = resnet_apply(
                {"params": p, "batch_stats": state["batch_stats"],
                 "config": cfg},
                x, train=True, compute_dtype=jnp.bfloat16,
                axis_name=hvd.GLOBAL_AXIS if distributed else None)
            onehot = jax.nn.one_hot(y, logits.shape[-1])
            loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
            return loss, ns

        (loss, ns), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"])
        if distributed:
            grads = hvd.allreduce(grads)
        updates, new_opt = opt.update(grads, opt_state, state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "batch_stats": ns}, new_opt, loss

    return step


def sync(x):
    """Force completion.  `block_until_ready` alone does not reliably block
    through remote PJRT transports (observed on the axon tunnel), so sync
    with an actual device→host transfer of a scalar."""
    jax.block_until_ready(x)
    return float(np.asarray(jax.tree_util.tree_leaves(x)[0]).ravel()[0])


def time_steps(compiled, state, opt_state, batch, warmup, iters):
    for _ in range(warmup):
        state, opt_state, loss = compiled(state, opt_state, batch)
    sync(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, opt_state, loss = compiled(state, opt_state, batch)
    sync(loss)
    dt = time.perf_counter() - t0
    return dt / iters, state, opt_state


def main():
    import horovod_tpu as hvd
    from horovod_tpu.models import resnet_init

    hvd.init()
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    # Reference benchmark: batch 64 per worker @ 224x224 (docs/benchmarks.rst
    # / pytorch_synthetic_benchmark.py default batch-size=32; tf_cnn uses 64).
    batch = 64 if on_tpu else 4
    image = 224 if on_tpu else 64
    warmup, iters = (3, 10) if on_tpu else (1, 3)
    log(f"platform={platform} devices={len(jax.devices())} "
        f"batch={batch} image={image}")

    rng = jax.random.PRNGKey(42)
    v = resnet_init(rng, 50, num_classes=1000)
    cfg = v["config"]
    opt = optax.sgd(0.0125, momentum=0.9)

    x = jax.random.normal(jax.random.PRNGKey(0), (batch, image, image, 3),
                          jnp.bfloat16).astype(jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, 1000)

    def fresh_state():
        vv = resnet_init(rng, 50, num_classes=1000)
        st = {"params": vv["params"], "batch_stats": vv["batch_stats"]}
        return st, opt.init(st["params"])

    # --- framework path: one SPMD program over the mesh ---
    state, opt_state = fresh_state()
    fw_step = hvd.data_parallel(build_step(opt, cfg, distributed=True))
    sb = hvd.shard_batch((x, y))
    t_fw, _, _ = time_steps(fw_step, state, opt_state, sb, warmup, iters)
    fw_imgsec = batch * hvd.size() / t_fw / hvd.size()  # per chip
    log(f"framework: {t_fw*1e3:.1f} ms/step, {fw_imgsec:.1f} img/s/chip")

    # --- raw-JAX baseline: same work, plain jit, no framework ---
    state, opt_state = fresh_state()
    raw_step = jax.jit(build_step(opt, cfg, distributed=False),
                       donate_argnums=(0, 1))
    t_raw, _, _ = time_steps(raw_step, state, opt_state, (x, y),
                             warmup, iters)
    raw_imgsec = batch / t_raw
    log(f"raw jax:   {t_raw*1e3:.1f} ms/step, {raw_imgsec:.1f} img/s/chip")

    print(json.dumps({
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(fw_imgsec, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(fw_imgsec / raw_imgsec, 4),
    }))


if __name__ == "__main__":
    main()
